#!/usr/bin/env bash
# The project lint gate (ISSUE 8): AST rules codified from the serving
# stack's recurring review findings (bare threading primitives,
# unknown failpoint names, wall-clock timing, jit outside the engine,
# recycle outside finally — `--list-rules` prints the table with the
# historical bug each rule encodes).
#
# Exit-code contract: 0 clean, 1 findings (printed as file:line RULE
# message), 2 internal lint error. scripts/tier1.sh runs this BEFORE
# pytest, so a lint regression fails tier-1 without burning a test run;
# run it alone while iterating:
#
#   bash scripts/lint.sh                  # the gate
#   bash scripts/lint.sh --list-rules     # rule table
#   bash scripts/lint.sh --show-allowed   # include pragma'd findings
cd "$(dirname "$0")/.." || exit 1
exec python -m distributedmnist_tpu.analysis "$@"
