#!/usr/bin/env bash
# The long-budget schedule-exploration sweep (ISSUE 11): 500 explored
# schedules per serve state machine (cache single-flight vs promote
# epoch, registry promote/rollback/eviction, batcher submit/shed/
# drain/stop, fleet pick/failover/drain-rejoin), emitting an
# ANALYSIS_r*.json round artifact (BENCH-style numbering) so analysis
# coverage has a trajectory like perf does.
#
#   bash scripts/explore.sh                 # 500 schedules/machine
#   bash scripts/explore.sh 2000            # a bigger budget
#   bash scripts/explore.sh 1 --machines cache --seed 123
#                                           # replay one failing seed
#
# Exit 0 clean, 1 on findings (each finding prints its replay seed —
# a failing interleaving is a seed, not a flake). The tier-1 gate runs
# the bounded --smoke preset instead (scripts/tier1.sh).
cd "$(dirname "$0")/.." || exit 1
schedules=500
if [[ "${1:-}" =~ ^[0-9]+$ ]]; then
    schedules="$1"
    shift
fi
exec env JAX_PLATFORMS=cpu python -m distributedmnist_tpu.analysis.explore \
    --schedules "$schedules" --emit "$@"
