#!/usr/bin/env python
"""Calibrate synthetic_mnist difficulty against canonical MNIST results.

Canonical published MNIST test accuracies (LeCun et al. 1998 + common
reproductions): linear ~92%, MLP 784-128(-ish)-10 ~97.5-98.4%, LeNet-5
~99.0-99.3%. The synthetic task should mirror that profile: MLP plateaus
BELOW 99%, LeNet-5 exceeds it — so the "wall-clock to 99%" harness on
synthetic data exercises the same model-capability cliff as real MNIST.

Sweeps (noise, jitter) over candidate values, trains MLP and LeNet on
each for --epochs, prints a table. Run on CPU:

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=/root/repo python scripts/calibrate_synthetic.py
"""

from __future__ import annotations

import argparse
import sys


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--train-n", type=int, default=60_000)
    p.add_argument("--test-n", type=int, default=10_000)
    p.add_argument("--grid", default="0.35:3,0.45:4,0.55:4,0.65:4,0.55:5")
    p.add_argument("--models", default="mlp,lenet")
    args = p.parse_args()

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.data.mnist import synthetic_mnist

    cands = []
    for item in args.grid.split(","):
        noise, jitter = item.split(":")
        cands.append((float(noise), int(jitter)))

    rows = []
    for noise, jitter in cands:
        data = synthetic_mnist(seed=0, train_n=args.train_n,
                               test_n=args.test_n, noise=noise,
                               jitter=jitter)
        accs = {}
        for model in args.models.split(","):
            cfg = Config(device="cpu", model=model, optimizer="adam",
                         learning_rate=2e-3, lr_schedule="cosine",
                         synthetic=True, batch_size=512,
                         epochs=args.epochs, eval_every=10 ** 9,
                         log_every=0, target_accuracy=None)
            out = trainer.fit(cfg, data=data)
            accs[model] = out["test_accuracy"]
            print(f"noise={noise} jitter={jitter} {model}: "
                  f"{out['test_accuracy']:.4f}", file=sys.stderr,
                  flush=True)
        rows.append((noise, jitter, accs))

    print(f"{'noise':>6} {'jitter':>6} " + " ".join(
        f"{m:>8}" for m in args.models.split(",")))
    for noise, jitter, accs in rows:
        print(f"{noise:>6} {jitter:>6} " + " ".join(
            f"{accs[m]:>8.4f}" for m in args.models.split(",")))


if __name__ == "__main__":
    main()
