#!/usr/bin/env bash
# The long-form compile-surface audit (ISSUE 12): the same static
# auditor scripts/tier1.sh gates on — static jit-cache-key universe
# closure (warmed == reachable for every dtype variant of both
# models), transfer/weak-type hazard scans, jaxpr fingerprints vs the
# committed snapshot — run with the ANALYSIS_r*.json round artifact
# emitted (BENCH-style numbering), so compile-surface coverage has a
# trajectory like perf and the explorer do.
#
#   bash scripts/jaxcheck.sh                  # audit + artifact
#   bash scripts/jaxcheck.sh --models mlp     # one model
#   bash scripts/jaxcheck.sh --update-snapshots --reason "why"
#                                             # after an INTENDED
#                                             # forward change
#   bash scripts/jaxcheck.sh --list-rules     # the JX rule table
#
# Exit 0 on a CLOSED clean surface, 1 on findings, 2 on internal
# error — the lint/explorer exit contract.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m distributedmnist_tpu.analysis.jaxcheck \
    --emit "$@"
