#!/usr/bin/env bash
# The canonical tier-1 gate, verbatim from ROADMAP.md ("Tier-1
# verify"). Builder, reviewer and CI all run THIS script instead of
# each retyping the command — if the gate ever changes, change
# ROADMAP.md and this file together (they must stay identical).
#
# Exit code is pytest's; DOTS_PASSED echoes the progress-dot count the
# driver compares across rounds.
#
# Marker note: the `-m 'not slow'` selection below INCLUDES the chaos,
# fleet and quant suites (tests/conftest.py registers the markers) —
# they are cheap and deterministic by design, so the tier-1 gate covers
# fault injection, the replica fleet, and the quantized inference fast
# path on every run. `pytest -m quant` selects the fast-path suite
# alone.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
