#!/usr/bin/env bash
# The canonical tier-1 gate, verbatim from ROADMAP.md ("Tier-1
# verify"). Builder, reviewer and CI all run THIS script instead of
# each retyping the command — if the gate ever changes, change
# ROADMAP.md and this file together (they must stay identical).
#
# Exit code is pytest's; DOTS_PASSED echoes the progress-dot count the
# driver compares across rounds.
#
# Marker note: the `-m 'not slow'` selection below INCLUDES the chaos,
# fleet, quant, analysis, trace, cache, cascade, tenant, gateway and
# autoscale suites
# (tests/conftest.py registers the markers) — they are cheap and
# deterministic by design, so the tier-1 gate covers fault injection,
# the replica fleet, the quantized inference fast path, the
# concurrency sanitizer/lint, the request tracer, the prediction-cache
# front layer, the confidence-gated cascade, the multi-tenant
# scheduler (quota admission, DRR fairness, EDF shedding, the
# two-model catalog), and the trace-replay/autoscaler control loop on
# every run.
# `pytest -m quant` / `-m analysis` / `-m trace` / `-m cache` /
# `-m cascade` / `-m tenant` / `-m gateway` / `-m autoscale` select
# those suites alone.
cd "$(dirname "$0")/.." || exit 1
# The project lint runs FIRST (ISSUE 8): a lint regression (bare
# threading primitive, unknown failpoint name, wall-clock timing, ...)
# fails the gate in ~a second instead of after a full pytest run.
# scripts/lint.sh exit codes: 0 clean, 1 findings, 2 lint error.
bash scripts/lint.sh || exit $?
# The schedule-explorer smoke (ISSUE 11): fixed seeds, a bounded
# budget per machine (<= 30 s total) over the four riskiest serve
# state machines — promote-vs-insert and leader-vs-follower races are
# PROVEN absent on the explored schedules, not sampled. Exit 1 on any
# finding (the summary prints a replay seed). scripts/explore.sh runs
# the 500-schedule long budget.
timeout -k 10 120 env JAX_PLATFORMS=cpu python -m distributedmnist_tpu.analysis.explore --smoke || exit $?
# The static compile-surface auditor (ISSUE 12): abstract-evaluate
# every forward the serving registry could dispatch and prove the jit
# cache-key universe CLOSED (warmed == reachable), transfer-clean,
# weak-type-free, and fingerprint-stable against the committed
# snapshot — before pytest spends a second. CPU-only, no device work,
# ~15 s. Exit 1 on findings; regenerate snapshots (with a reason) via
# scripts/jaxcheck.sh after an INTENDED forward change.
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m distributedmnist_tpu.analysis.jaxcheck || exit $?
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
