#!/usr/bin/env python
"""Slice-by-slice profile of the fused train step on the default backend.

Isolates each component of the hot-loop step (scan floor, batch gather
variants, forward, backward, optimizer variants, gather/compute
double-buffering) as its OWN scanned+jitted program and times each with
the honest fetch barrier (StepTimer.barrier — block_until_ready lies on
this host's relay backend). Prints ONE JSON record (ms/iter keyed by
variant) on stdout plus a summary table on stderr.

Runs in a stall-supervised worker subprocess like bench.py (the relay's
claim leg can wedge a fresh process forever; the supervisor kills and
retries on silence) — --inline bypasses supervision.

Usage: python scripts/profile_step.py [--batch 512] [--k 256]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable as `python scripts/profile_step.py` from anywhere: python puts
# scripts/ (not the repo root) on sys.path for a script invocation, so
# the package import below would otherwise need PYTHONPATH set by hand.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def mark(msg):
    print(f"profile: {msg}", file=sys.stderr, flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--k", type=int, default=256, help="scan length")
    p.add_argument("--blocks", type=int, default=4)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--only", default=None,
                   help="comma-separated variant names to run")
    p.add_argument("--stall-timeout", type=float, default=300.0,
                   help="kill+retry the worker if it is silent this long")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="worker attempts before giving up")
    p.add_argument("--inline", action="store_true",
                   help="run in-process (no supervisor subprocess)")
    args = p.parse_args()

    from distributedmnist_tpu.utils import supervise

    if not args.inline and not supervise.is_worker():
        return supervise.run_supervised(
            os.path.abspath(__file__), list(sys.argv[1:]),
            accept=supervise.json_record_acceptor("ms_per_iter"),
            stall_timeout=args.stall_timeout, attempts=args.max_attempts)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.data import load_mnist
    from distributedmnist_tpu.data.loader import DeviceDataset, IndexStream
    from distributedmnist_tpu.parallel import make_mesh, replicated
    from distributedmnist_tpu.trainer import (
        init_state, make_train_step, _forward_loss, _make_one_step)
    from distributedmnist_tpu.utils import StepTimer, enable_compilation_cache

    enable_compilation_cache()
    devs = jax.devices()
    mark(f"backend up: {len(devs)}x {devs[0].platform}")
    mesh = make_mesh(devs)
    B, K = args.batch, args.k

    data = load_mnist(None, synthetic=True, seed=0)
    ds = DeviceDataset(data, mesh)
    model = models.build("lenet", platform=devs[0].platform)
    tx = optax.adam(1e-3)
    tx_flat = optax.flatten(optax.adam(1e-3))
    loss_fn = _forward_loss(model, jnp.float32)

    # int32-packed pixels — the PRODUCTION pack/unpack (data/packing.py),
    # so these timings describe the shipped layout, not a local variant.
    from distributedmnist_tpu.data.packing import pack_rows, unpack_rows
    train_xp = jax.device_put(pack_rows(data["train_x"]), replicated(mesh))
    unpack = unpack_rows

    def loss_packed(params, words, y):
        logits = model.apply({"params": params}, unpack(words))
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    idx_host = np.random.default_rng(0).integers(
        0, ds.train_n, size=(K, B)).astype(np.int32)
    idx = jax.device_put(idx_host, replicated(mesh))

    one_step = _make_one_step(loss_fn, tx)
    one_step_flat = _make_one_step(loss_fn, tx_flat)

    def fresh(tx_):
        return lambda: jax.device_put(
            init_state(jax.random.PRNGKey(0), model, tx_,
                       jnp.zeros((1, 28, 28, 1))), replicated(mesh))
    mk_state, mk_state_flat = fresh(tx), fresh(tx_flat)
    zero = lambda: jnp.zeros(())

    def scanned(body):
        def f(carry, idx):
            return jax.lax.scan(body, carry, idx)
        return jax.jit(f, donate_argnums=0)

    # --- variants ----------------------------------------------------
    variants = {}

    def v_empty(carry, ix):
        return carry + ix[0].astype(jnp.float32), ix[0]
    variants["empty"] = (scanned(v_empty), zero)

    def v_gather_u8(carry, ix):
        x = jnp.take(ds.train_x, ix, axis=0)
        return carry + x.astype(jnp.float32).sum(), None
    variants["gather_u8"] = (scanned(v_gather_u8), zero)

    def v_gather_packed(carry, ix):
        w = jnp.take(train_xp, ix, axis=0)
        return carry + unpack(w).sum(), None
    variants["gather_packed"] = (scanned(v_gather_packed), zero)

    def v_fwd(state_c, ix):
        x = jnp.take(ds.train_x, ix, axis=0)
        y = jnp.take(ds.train_y, ix, axis=0)
        loss = loss_fn(state_c.params, x, y)
        return state_c, loss
    variants["fwd"] = (scanned(v_fwd), mk_state)

    const_x = jnp.take(ds.train_x, idx[0], axis=0)
    const_y = jnp.take(ds.train_y, idx[0], axis=0)

    def v_fwd_nogather(state_c, ix):
        # XOR with a scanned scalar defeats loop-invariant hoisting of the
        # whole forward while adding only one cheap elementwise op.
        x = const_x ^ (ix[0] & 1).astype(jnp.uint8)
        loss = loss_fn(state_c.params, x, const_y)
        return state_c, loss
    variants["fwd_nogather"] = (scanned(v_fwd_nogather), mk_state)

    def v_fwdbwd(state_c, ix):
        x = jnp.take(ds.train_x, ix, axis=0)
        y = jnp.take(ds.train_y, ix, axis=0)
        loss, grads = jax.value_and_grad(loss_fn)(state_c.params, x, y)
        leaf = jax.tree.leaves(grads)[0]
        return state_c, loss + leaf.sum().astype(jnp.float32)
    variants["fwdbwd"] = (scanned(v_fwdbwd), mk_state)

    def v_full(state_c, ix):
        x = jnp.take(ds.train_x, ix, axis=0)
        y = jnp.take(ds.train_y, ix, axis=0)
        return one_step(state_c, x, y)
    variants["full_adam"] = (scanned(v_full), mk_state)

    def v_full_flat(state_c, ix):
        x = jnp.take(ds.train_x, ix, axis=0)
        y = jnp.take(ds.train_y, ix, axis=0)
        return one_step_flat(state_c, x, y)
    variants["full_adam_flat"] = (scanned(v_full_flat), mk_state_flat)

    one_step_flat_packed = _make_one_step(
        lambda p, w, y: loss_packed(p, w, y), tx_flat)

    def v_full_flat_packed(state_c, ix):
        w = jnp.take(train_xp, ix, axis=0)
        y = jnp.take(ds.train_y, ix, axis=0)
        return one_step_flat_packed(state_c, w, y)
    variants["full_flat_packed"] = (scanned(v_full_flat_packed), mk_state_flat)

    # double-buffered: body consumes the carried batch, gathers the next
    def v_dbuf_body(carry, ix):
        state_c, xb, yb = carry
        new_state, loss = one_step_flat(state_c, xb, yb)
        xn = jnp.take(ds.train_x, ix, axis=0)
        yn = jnp.take(ds.train_y, ix, axis=0)
        return (new_state, xn, yn), loss

    def dbuf_fn(carry, idx):
        state_c = carry
        x0 = jnp.take(ds.train_x, idx[0], axis=0)
        y0 = jnp.take(ds.train_y, idx[0], axis=0)
        (state_c, _, _), losses = jax.lax.scan(
            v_dbuf_body, (state_c, x0, y0), jnp.roll(idx, -1, axis=0))
        return state_c, losses
    variants["full_flat_dbuf"] = (jax.jit(dbuf_fn, donate_argnums=0), mk_state_flat)

    def sync_of(carry, out):
        # ALWAYS fetch something that depends on every iteration's work:
        # the stacked per-step outputs when present, else the carry.
        return out if out is not None else carry

    only = set(args.only.split(",")) if args.only else None
    results = {}
    for name, (fn, mk_carry) in variants.items():
        if only and name not in only:
            continue
        mark(f"{name}: compiling")
        carry = mk_carry()
        carry, out = fn(carry, idx)            # compile + warmup
        StepTimer.barrier(sync_of(carry, out))
        times = []
        for r in range(args.repeats):
            # Liveness for the supervisor: at large --batch/--k/--blocks
            # one repeat's barrier wait is long, and silence past the
            # stall timeout would kill a healthy worker (the stderr
            # print costs microseconds against a multi-second repeat).
            mark(f"{name}: repeat {r + 1}/{args.repeats}")
            t0 = time.perf_counter()
            for _ in range(args.blocks):
                carry, out = fn(carry, idx)
            StepTimer.barrier(sync_of(carry, out))
            times.append((time.perf_counter() - t0)
                         / (args.blocks * K) * 1e3)
        ms = sorted(times)[len(times) // 2]
        results[name] = ms
        mark(f"{name}: {ms:.4f} ms/iter  (all: "
             + ", ".join(f"{t:.4f}" for t in times) + ")")

    floor = results.get("empty", 0.0)
    print(json.dumps({"batch": B, "k": K, "floor_ms": floor,
                      "ms_per_iter": results}))
    for name, ms in results.items():
        net = ms - floor
        print(f"{name:22s} {ms:8.4f} ms  (net {net:8.4f})  "
              f"{B / ms * 1000:10.0f} img/s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
