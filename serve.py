#!/usr/bin/env python
"""serve.py — request-shaped inference entrypoint over the batched
serving engine (distributedmnist_tpu/serve/), the forward-only sibling
of train.py.

Two modes:

- selftest (default): drive --selftest N synthetic requests of mixed
  sizes through the dynamic batcher in-process, then print one JSON
  summary line ({"metric": "serve_selftest", ...}) — the cheap
  end-to-end gate, and what `python serve.py` does out of the box.
- --port P: serve HTTP on P (0 picks an ephemeral port, announced as a
  {"metric": "serve_ready", "port": ...} JSON line on stdout). stdlib
  http.server only — the container installs nothing.
- --gateway N (ISSUE 19, serve/gateway.py): this process becomes a
  front door instead — it spawns N full serve.py workers (every other
  serving flag forwards to them verbatim), routes /predict across
  them on a consistent-hash ring keyed like the prediction cache
  (hot keys shard, not duplicate), and coordinates fleet-wide
  promote through a two-phase cluster epoch. Announced as
  {"metric": "gateway_ready", ...}; POST /cluster/epoch is the
  worker-side receiving end, and every worker response then carries
  X-Cluster-Epoch.

    POST /predict        body = raw uint8 pixels, n*784 bytes ->
                         {"classes": [...], "n": n, "version": ...}
                         503 + Retry-After when the queue is past its
                         backpressure watermark OR no warmed model is
                         live yet (shed, don't melt); the optional
                         X-Deadline-Ms header is the client's latency
                         budget — a request whose deadline expires
                         before dispatch is shed with a fast 504
                         (zero device work) instead of computing an
                         answer nobody is waiting for. Retry-After on
                         every shed is derived from the live pipeline
                         (effective coalescing wait + in-flight depth
                         at the measured batch service time)
    GET  /metrics        current ServeMetrics snapshot (JSON), incl.
                         per-version populations + shadow comparisons;
                         ?format=prometheus (or an Accept: text/plain
                         scrape) returns the Prometheus text
                         exposition — stable # TYPE'd counters/gauges/
                         summaries and, under --serve-trace, per-stage
                         duration histograms
    GET  /trace          (--serve-trace) Chrome trace-event JSON of the
                         retained request traces — loads directly in
                         chrome://tracing / Perfetto. Every /predict
                         response then carries X-Trace-Id, and a
                         request sent with `X-Server-Timing: 1` gets a
                         Server-Timing stage breakdown on its response
                         the optional X-Accuracy-Class header picks
                         the accuracy/latency operating point under
                         --serve-cascade: fast|balanced|exact (400 on
                         anything else, or when no cascade is serving)
    GET  /healthz        real state: {"ok", "state":
                         warming|running|draining, "live_version",
                         "pending_rows", "inflight_batches",
                         "versions"}; 503 until a warmed model is live
    GET  /models         model registry listing + routing table
    POST /models/load    {"dir"?: str, "version"?: str} — params-only
                         restore of the latest committed checkpoint,
                         pre-warm every bucket OFF the hot path; the
                         new version becomes promotable, NOT live
    POST /models/promote {"version": str, "mode"?: "live"|"shadow"|
                         "canary", "fraction"?: float,
                         "infer_dtype"?: str} — atomic hot-swap
                         (live; infer_dtype routes a parity-gated
                         bf16/int8 variant instead of the f32 base),
                         or route a fraction as shadow (compare +
                         discard) / canary (real)
    POST /replicas/{id}/drain    take one fleet replica out of the
                         dispatch pick set (in-flight work finishes;
                         version rolls still fan out to it)
    POST /replicas/{id}/rejoin   return it with a fresh health slate
                         (both 409 unless --serve-replicas >= 2)

SIGHUP = load latest checkpoint from --checkpoint-dir and promote it
(the operator's one-signal model roll). The server starts serving HTTP
immediately in state "warming" (healthz 503, /predict 503) and flips to
"running" only after the initial model has every bucket compiled — the
Clockwork discipline: no traffic before the programs are warm.

Periodic {"metric": "serve_stats", ...} heartbeat lines go to stdout
(--metrics-every), so utils/supervise.py's json_record_acceptor can
watch a serving process exactly as it watches the bench. SIGTERM/SIGINT
flip state to "draining" (healthz 503 — load balancers stop sending),
shut the server down cleanly and print a final summary line.

Model/params come from Config: --checkpoint-dir restores trained params
(params-only — no optimizer slots are read for serving); otherwise
params are fresh-init (load tests). Batching knobs: --serve-max-batch,
--serve-max-wait-us, --serve-queue-depth, --serve-max-inflight
(config.py); --serve-max-versions bounds resident warmed versions;
--serve-slo-ms arms the SLO-aware adaptive coalescing controller and
--no-adaptive pins the static wait (serve/scheduler.py — the cost-model
batch former is always on; it degrades to single-dispatch when the cost
table is absent).
--request-timeout bounds how long an HTTP client thread may wait on its
future before a 504 — a wedged dispatch pipeline must shed its waiters,
not hold ThreadingHTTPServer threads forever.

Resilience (ISSUE 5, serve/resilience.py): a failed multi-request
dispatch is bisected so only the poison request 500s (--no-bisect
restores whole-cohort failure); every request outcome feeds a
per-version circuit breaker (--serve-breaker-*) whose trip demotes the
live version and auto-promotes the newest healthy resident, emitting a
rollback event visible in /healthz and GET /models. --serve-faults
installs a deterministic fault-injection schedule (serve/faults.py) for
chaos drills; without it every woven failpoint is inert.

Inference fast path (ISSUE 7, serve/quantize.py): --serve-infer-dtype
{float32,bfloat16,int8,auto} picks the serving precision. float32 is the
training-identical reference forward; bfloat16/int8 run the quantized +
fused inference path, which takes traffic only after the registry's
zero-compile prove-it pass AND an accuracy-parity gate against the f32
reference (argmax agreement >= 0.995 + relative logit diff thresholds,
PARITY.md); a refused variant stays off traffic with its reason in
GET /models. auto serves the cheapest parity-passing variant by the
warmup-measured bucket cost tables. /healthz and GET /models report
live_infer_dtype so an operator can tell which precision is live.

Prediction cache (ISSUE 10, serve/cache.py): --serve-cache puts a
content-hash front layer before the batcher — a bounded LRU keyed by
(live version, infer_dtype, sha256 of the input bytes). Repeats of a
hot key are served sub-millisecond with ZERO device work (still
version-tagged, still metered, still X-Trace-Id'd); concurrent
identical misses collapse onto ONE in-flight computation
(single-flight: the leader dispatches, followers share its bytes, a
leader failure fails them with the leader's error and is never
cached). The registry invalidates the cache atomically on every
promote/rollback/dtype activation, entries re-check their computing
version at read, so a stale-version hit is impossible.
--serve-cache-capacity bounds resident entries; --serve-dedup
additionally collapses identical rows inside one coalesced drain
(dispatch once, fan out). /metrics exposes hit/miss/collapse/evict
counters and the hit ratio (JSON `cache` block + dmnist_serve_cache_*
Prometheus series).

Confidence-gated cascade (ISSUE 17, serve/cascade.py):
--serve-cascade fronts the pipeline with a two-stage dispatcher: the
cheap parity-gated variant (int8 by default) answers every row whose
softmax margin clears a confidence threshold calibrated on the held-out
parity batch; uncertain rows escalate to the f32 reference THROUGH THE
NORMAL COALESCING PATH (escalations are just requests — batch forming,
in-flight window, cache keying and bisection semantics unchanged). The
cascade takes traffic only after an end-to-end composed-accuracy gate:
the cascade's final answers must match f32 within the PARITY.md bar.
Per-request X-Accuracy-Class picks the operating point — "fast" (cheap
variant only), "balanced" (the cascade; default), "exact" (f32 only);
unknown values 400. --serve-cascade-threshold overrides the calibrated
threshold (the same gate judges the override), and POST /models/promote
accepts "cascade_threshold" for per-roll overrides. /healthz and GET
/models expose the calibrated threshold + per-version cascade state;
/metrics gains dmnist_serve_cascade_* series (per-class requests,
per-stage rows, escalation fraction).

Fast lane (ISSUE 14, serve/batcher.py + engine.dispatch_fast):
--serve-fastlane opens the single-request low-latency bypass — a
submit that finds the queue empty and a free in-flight slot dispatches
immediately on the caller's thread (no coalesce timer, no queue
hand-offs; device-resident staging for small buckets, priced at
warmup), falling back to the coalescing path the moment contention
appears. /metrics reports the lane split (`fastpath`);
--serve-cache-ttl-s adds bounded staleness to the prediction cache
(entries expire by monotonic age; expired hits count as misses,
`dmnist_serve_cache_expired_total`).

Tracing (ISSUE 9, serve/trace.py): --serve-trace installs the
per-request span tracer. Each request's path (queue wait, staging,
device window, fetch, rescues, bisect retries) is recorded as a span
tree; errored and over-SLO requests are ALWAYS retained (head sampling
--serve-trace-sample only thins the OK traces), the ring is bounded at
--serve-trace-capacity, and the same spans feed the /metrics per-stage
histograms. Default off: every woven hook is one None check.

Replica fleet (ISSUE 6, serve/fleet.py): --serve-replicas N puts N
engine replicas (mesh slices when devices divide evenly, logical
replicas otherwise) behind a health-tracked load-balancing dispatcher
with per-replica in-flight windows (--serve-replica-inflight), failover
redispatch (a batch whose replica dies at dispatch/fetch retries once
on a healthy sibling — replica faults cost latency, not errors), an
optional hedged-tail duplicate (--serve-hedge), and per-replica circuit
breakers that route around a sick replica without touching the version.
/healthz and /metrics carry the per-replica state; every shed response's
Retry-After is capped at --serve-retry-after-cap-s (integer seconds per
RFC 9110).
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time

from distributedmnist_tpu import config as config_lib
from distributedmnist_tpu.analysis.locks import make_lock, make_thread

IMAGE_BYTES = 28 * 28

log = logging.getLogger("distributedmnist_tpu")


class ServerState:
    """The serving process's lifecycle phase, reported by /healthz.
    warming -> running -> draining; "failed" when the initial model
    load/warm died (the server stays up so healthz can say WHY it is
    unhealthy instead of connection-refused). All transitions go through
    the locked methods: draining is TERMINAL, and a check-then-set from
    an unsynchronized handler thread must never resurrect a
    shutting-down server to "running"."""

    def __init__(self):
        self._lock = make_lock("serve.state")
        self.phase = "warming"
        # Process start, wall clock: /healthz reports it (ISO 8601) so
        # fleet-level probes and the bench ledger can tell a RESTARTED
        # worker (stamp reset) from a RECOVERED one.
        # lint: allow[DML004] wall-clock birth stamp for the ISO healthz field only
        self.started_at = time.time()
        # uptime_s derives from the monotonic clock (ISSUE 8 lint
        # DML004 finding, fixed): wall-clock elapsed math would jump
        # with every NTP step — an uptime that moves backwards reads
        # as a restart that never happened.
        self._started_mono = time.monotonic()
        # Cluster epoch (ISSUE 19): the fleet-wide version-visibility
        # token a gateway assigns this worker. None on a standalone
        # server (no epoch stamps); an integer once a gateway's
        # fan-out lands. Mutated ONLY via apply_cluster_epoch — lint
        # DML018 enforces the containment.
        self._cluster_epoch = None

    def cluster_epoch(self):
        with self._lock:
            return self._cluster_epoch

    def mark_running(self) -> None:
        """warming/failed -> running (no-op from draining)."""
        with self._lock:
            if self.phase in ("warming", "failed"):
                self.phase = "running"

    def mark_failed(self) -> None:
        """warming -> failed (no-op once running or draining)."""
        with self._lock:
            if self.phase == "warming":
                self.phase = "failed"

    def begin_drain(self) -> None:
        with self._lock:
            self.phase = "draining"

    def healthz(self, registry, batcher) -> tuple[int, dict]:
        live = registry.live_version()
        # Circuit-breaker rollbacks (ISSUE 5) are surfaced here, not
        # just logged: a load balancer's health poll is often the first
        # thing an operator looks at after an availability dip, and
        # "the breaker auto-rolled v7 back to v6 at 14:02" is the story.
        events = (registry.events() if hasattr(registry, "events")
                  else [])
        # `rollbacks` counts COMPLETED rollbacks only (must agree with
        # metrics.resilience.rollbacks); last_rollback shows the most
        # recent attempt of either kind — a FAILED rollback (no healthy
        # fallback) is exactly what an operator must see, and its
        # "event": "rollback_failed" / "to": null disambiguate it.
        attempts = [e for e in events
                    if e.get("event", "").startswith("rollback")]
        rollbacks = [e for e in attempts if e.get("event") == "rollback"]
        # Recovery is observable, not sticky: a warmed model going live
        # through ANY path (initial warm thread, admin load+promote,
        # SIGHUP) flips warming/failed -> running — an operator who
        # repairs a bad boot checkpoint via the admin API must not be
        # left permanently 503. Draining stays terminal (mark_running
        # refuses it under the lock, so a SIGTERM racing this poll can
        # never be clobbered back to 200).
        if live is not None:
            self.mark_running()
        with self._lock:
            phase = self.phase
        ok = phase == "running" and live is not None
        import datetime
        desc = registry.describe()
        payload = {
            "ok": ok,
            "state": phase,
            "started_at": datetime.datetime.fromtimestamp(
                self.started_at,
                datetime.timezone.utc).isoformat(timespec="seconds"),
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
            "live_version": live,
            # which precision the live engines serve (ISSUE 7
            # satellite): float32 reference vs a gated bf16/int8
            # variant — None while warming. The registry's describe()
            # already computes it getattr-safely (test doubles lack the
            # field; .get keeps them working).
            "live_infer_dtype": desc.get("live_infer_dtype"),
            "pending_rows": batcher.pending_rows(),
            "inflight_batches": batcher.inflight_batches(),
            "versions": len(desc["versions"]),
            "rollbacks": len(rollbacks),
            "last_rollback": attempts[-1] if attempts else None,
            # Cluster epoch (ISSUE 19): None standalone, the gateway's
            # fan-out value once this process serves as a fleet worker.
            "cluster_epoch": self.cluster_epoch(),
        }
        # Silicon provenance (ISSUE 19): a gateway bench has no
        # in-process engine factory to ask, so the worker reports what
        # it runs on — bench.py's cross-silicon baseline refusal reads
        # these. getattr-safe: registry test doubles carry no factory.
        factory = getattr(registry, "factory", None)
        if factory is not None:
            try:
                payload["backend"] = factory.platform
                payload["device_kind"] = str(
                    factory.mesh.devices.flat[0].device_kind)
            except Exception:
                pass
        # Cascade state of the LIVE version (ISSUE 17): the calibrated
        # confidence threshold, cheap stage dtype and gate verdict —
        # None while warming or when no cascade is enabled. The fleet
        # probe reading this learns whether "balanced" requests are
        # actually cascading or degrading to the plain live route.
        live_desc = next((v for v in desc["versions"]
                          if isinstance(v, dict)
                          and v.get("version") == live), None)
        payload["cascade"] = (live_desc or {}).get("cascade")
        # Replica fleet state (ISSUE 6): per-replica health/load plus
        # the failover/hedge counters — the first thing to read after
        # an availability dip is WHICH replica was sick and whether the
        # fleet routed around it.
        fleet = registry.router if hasattr(registry, "router") else None
        if getattr(fleet, "n_replicas", 1) > 1:
            snap = fleet.snapshot()
            payload["replicas"] = snap["replicas"]
            payload["failovers"] = snap["failovers"]
        return (200 if ok else 503), payload


def shed_retry_after_s(batcher, cap_s: float = 30.0) -> int:
    """The Retry-After value for every shed response (watermark 503,
    no-live-model 503, deadline 504), derived from live pipeline state
    instead of a hardcoded guess: the current effective coalescing wait
    (where the adaptive controller actually sits, not the configured
    cap) plus the in-flight depth priced at the measured full-batch
    service time — roughly when the pipeline will have worked off what
    it already holds. Emitted as INTEGER seconds per RFC 9110 (the
    delay-seconds grammar has no fractions), floored at 1 and capped at
    `cap_s` (serve_retry_after_cap_s): the derived value is unbounded
    when the window is deep and a measured batch cost spikes, and a
    client told to come back in ten minutes simply leaves."""
    import math

    wait_s = (batcher.controller.effective_wait_s()
              if batcher.controller is not None
              else batcher.max_wait_s)
    costs_fn = getattr(batcher.engine, "bucket_costs", None)
    costs = costs_fn() if callable(costs_fn) else {}
    svc_s = max(costs.values()) if costs else 0.0
    depth = batcher.inflight_batches()
    cap = max(1, int(cap_s))
    return max(1, min(cap, math.ceil(wait_s + (depth + 1) * svc_s)))


def apply_cluster_epoch(state, cache, epoch: int) -> int:
    """The worker-side receiving end of the gateway's cluster-epoch
    fan-out — with Gateway.promote_fanout, the ONLY code allowed to
    mutate the epoch (lint DML018: any other assignment could move a
    worker's epoch outside the two-phase promote barrier and re-open
    the mixed-version window). Aligns the prediction cache's
    invalidation epoch in the same step, so entries computed under the
    previous fleet version can never serve under the new one."""
    with state._lock:
        state._cluster_epoch = epoch
    if cache is not None:
        cache.align_epoch(epoch, reason=f"cluster epoch {epoch}")
    return epoch


def _selftest(batcher, metrics, n_requests: int, max_batch: int) -> dict:
    import numpy as np

    from distributedmnist_tpu.serve import Rejected

    rng = np.random.default_rng(0)
    sizes = [int(rng.integers(1, max(2, min(max_batch, 32))))
             for _ in range(n_requests)]
    futures = []
    rejected = 0
    for n in sizes:
        x = rng.integers(0, 256, (n, IMAGE_BYTES), dtype=np.uint8)
        try:
            futures.append((n, batcher.submit(x)))
        except Rejected:
            rejected += 1
    for n, f in futures:
        out = f.result(timeout=120)
        assert out.shape == (n, 10), (out.shape, n)
    return {"metric": "serve_selftest", "requests_driven": n_requests,
            "rejected_at_submit": rejected, **metrics.snapshot()}


def _sanitizer_block() -> dict:
    """The concurrency sanitizer's findings for the summary lines, when
    one is installed (DMNIST_SANITIZE=1 — ISSUE 8): a local run that
    tripped a lock-order cycle or leaked a staging buffer must say so
    in its exit record, not only inside pytest."""
    from distributedmnist_tpu.analysis import sanitize

    san = sanitize.active_sanitizer()
    if san is None:
        return {}
    # Let the pipeline settle first (assert_clean's contract is "after
    # drain"): a snapshot taken the instant the last future resolved
    # could read a transient +1 as a leak.
    san.wait_drained(timeout_s=2.0)
    rep = san.report()
    clean = not any(rep.values())
    if not clean:
        log.warning("concurrency sanitizer findings: %s",
                    {k: v for k, v in rep.items() if v})
    return {"sanitizer": {"clean": clean, **rep}}


def _http_serve(batcher, metrics, registry, state, port: int,
                metrics_every: float, request_timeout: float,
                warm, retry_after_cap_s: float = 30.0,
                infer_dtype_choice: str = "float32",
                front=None, cache=None, cascade: bool = False,
                cascade_threshold=None, scheduler=None) -> dict:
    import concurrent.futures
    import math
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from distributedmnist_tpu.serve import (DeadlineExceeded, NoLiveModel,
                                            QuotaExceeded, Rejected,
                                            prometheus_exposition)
    from distributedmnist_tpu.serve import trace as trace_lib
    from distributedmnist_tpu.serve.cascade import ACCURACY_CLASSES

    max_body = registry.factory.max_batch * IMAGE_BYTES
    # The submit target: the prediction-cache front layer when
    # --serve-cache is on (ISSUE 10 — hits resolve without touching
    # the pipeline, identical concurrent misses collapse), the bare
    # batcher otherwise. Queue gauges always read the batcher itself.
    submit_to = front if front is not None else batcher
    # The replica fleet, when serving one (--serve-replicas >= 2):
    # admin drain/rejoin and the /metrics fleet block hang off it.
    fleet = (registry.router
             if getattr(registry.router, "n_replicas", 1) > 1 else None)

    def retry_after() -> dict:
        return {"Retry-After": str(
            shed_retry_after_s(batcher, retry_after_cap_s))}
    # Serializes admin mutations from HTTP/SIGHUP threads so two
    # concurrent loads can't interleave their registry side effects
    # mid-request (the registry's own lock already protects state; this
    # one keeps *responses* coherent, e.g. load-then-promote scripts).
    # blocking_ok: it deliberately holds across multi-second restores
    # and warmups — admin threads only, never the dispatch path.
    admin_lock = make_lock("serve.admin", blocking_ok=True)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # requests are metered, not
            pass                             # per-line logged

        def _send(self, code: int, payload: dict,
                  extra: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str,
                       content_type: str = "text/plain; "
                                           "version=0.0.4; "
                                           "charset=utf-8") -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            if length == 0:
                return {}
            raw = self.rfile.read(length)
            body = json.loads(raw) if raw.strip() else {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body

        def _wants_prometheus(self) -> bool:
            """`?format=prometheus` or a text/plain Accept (the
            standard scrape shape) selects the text exposition; the
            JSON snapshot stays the default for humans and tests."""
            from urllib.parse import parse_qs, urlsplit
            q = parse_qs(urlsplit(self.path).query)
            if q.get("format", [None])[0] == "prometheus":
                return True
            accept = self.headers.get("Accept", "")
            return ("text/plain" in accept
                    and "application/json" not in accept)

        def do_GET(self):
            if self.path == "/healthz":
                code, payload = state.healthz(registry, batcher)
                self._send(code, payload)
            elif self.path == "/trace" or self.path.startswith("/trace?"):
                tracer = trace_lib.active()
                if tracer is None:
                    self._send(409, {
                        "error": "tracing is not enabled; restart with "
                                 "--serve-trace"})
                else:
                    # Chrome trace-event JSON: loads directly in
                    # chrome://tracing / Perfetto.
                    self._send(200, tracer.export_chrome())
            elif (self.path == "/metrics"
                  or self.path.startswith("/metrics?")):
                if self._wants_prometheus():
                    tracer = trace_lib.active()
                    self._send_text(200, prometheus_exposition(
                        metrics.snapshot(),
                        trace_stages=(tracer.snapshot()["stages"]
                                      if tracer is not None else None),
                        gauges={
                            "pending_rows": batcher.pending_rows(),
                            "inflight_batches":
                                batcher.inflight_batches(),
                        },
                        cache=(cache.stats() if cache is not None
                               else None)))
                    return
                # The full ServeMetrics snapshot PLUS point-in-time
                # pipeline gauges and the adaptive controller's state —
                # the operator's one-stop view, so nobody has to scrape
                # the stdout heartbeat lines for queue depth or the
                # current effective wait.
                payload = metrics.record()
                payload["queue"] = {
                    "pending_rows": batcher.pending_rows(),
                    "inflight_batches": batcher.inflight_batches(),
                    "max_inflight": batcher.max_inflight,
                    "queue_depth_watermark": batcher.queue_depth,
                }
                payload["adaptive"] = (
                    batcher.controller.snapshot()
                    if batcher.controller is not None else None)
                # the prediction-cache front layer's counters + hit
                # ratio (ISSUE 10; None without --serve-cache)
                payload["cache"] = (cache.stats()
                                    if cache is not None else None)
                # the breaker's live windows (per-version volume /
                # failures / cooldown) — the resilience counters in the
                # snapshot say what already happened, this says what
                # the breaker currently believes
                payload["resilience_policy"] = (
                    batcher.resilience.snapshot()
                    if batcher.resilience is not None else None)
                # the fleet's per-replica load/health + failover and
                # hedge counters (None on a single-replica server)
                payload["fleet"] = (fleet.snapshot()
                                    if fleet is not None else None)
                # the tracer's counters + per-stage duration
                # histograms, derived from the same spans GET /trace
                # exports (None without --serve-trace)
                tracer = trace_lib.active()
                payload["trace"] = (tracer.snapshot()
                                    if tracer is not None else None)
                # the global scheduler's live view (ISSUE 18; None on
                # a single-model server) — same dict GET /tenants
                # serves
                payload["tenancy"] = (scheduler.snapshot()
                                      if scheduler is not None else None)
                # this process's XLA compile-event count (ISSUE 19): a
                # gateway bench asserts recompiles_after_warmup == 0 on
                # EVERY worker by steady-window deltas of this value —
                # it has no in-process CompileCounter to read.
                from distributedmnist_tpu.utils import CompileCounter
                payload["compiles_total"] = (
                    CompileCounter.instance().snapshot())
                self._send(200, payload)
            elif self.path == "/models":
                self._send(200, registry.describe())
            elif self.path == "/tenants":
                # The scheduler's own view (ISSUE 18): per-tenant
                # admission config + live DRR accounting, catalog
                # residency. 409 without the tenancy layer — the
                # resource genuinely does not exist on this server.
                if scheduler is None:
                    self._send(409, {
                        "error": "multi-tenant serving is off; "
                                 "--serve-tenants/--serve-models "
                                 "enables it"})
                else:
                    self._send(200, scheduler.snapshot())
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path == "/predict":
                self._predict()
            elif self.path == "/models/load":
                self._models_load()
            elif self.path == "/models/promote":
                self._models_promote()
            elif self.path == "/cluster/epoch":
                self._cluster_epoch_admin()
            elif self.path.startswith("/replicas/"):
                self._replicas_admin()
            elif self.path.startswith("/tenants/"):
                self._tenants_admin()
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        # -- admin: replica fleet ---------------------------------------

        def _replicas_admin(self):
            """POST /replicas/{id}/drain|rejoin — take one replica out
            of the dispatch pick set (in-flight work finishes; a
            version roll still fans out to it so rejoin never serves a
            stale version) or bring it back with a fresh health slate.
            409 on a single-replica server (there is no fleet to
            administer) and on draining the last active replica."""
            parts = self.path.strip("/").split("/")
            if len(parts) != 3 or parts[2] not in ("drain", "rejoin"):
                self._send(404, {"error": "want POST /replicas/{id}/"
                                          "drain or /replicas/{id}/"
                                          "rejoin"})
                return
            _, rid, action = parts
            if fleet is None:
                self._send(409, {"error": "this server runs a single "
                                          "replica; --serve-replicas "
                                          ">= 2 enables the fleet"})
                return
            try:
                with admin_lock:
                    snap = (fleet.drain(rid) if action == "drain"
                            else fleet.rejoin(rid))
                self._send(200, {"action": action, "replica": snap})
            except KeyError as e:
                self._send(404, {"error": str(e)})
            except RuntimeError as e:
                # e.g. draining the last active replica: a rule
                # refusal, not a server fault
                self._send(409, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        # -- admin: tenant quotas (ISSUE 18) ---------------------------

        def _tenants_admin(self):
            """POST /tenants/{id}/quota {"qps": x, "burst": y} — live-
            update one SLO class's token bucket. The bucket refills to
            the new burst so a loosened quota takes effect NOW. 404 for
            an unknown tenant, 400 for malformed numbers, 409 without
            the tenancy layer."""
            parts = self.path.strip("/").split("/")
            if len(parts) != 3 or parts[2] != "quota":
                self._send(404, {"error": "want POST /tenants/{id}/"
                                          "quota"})
                return
            if scheduler is None:
                self._send(409, {
                    "error": "multi-tenant serving is off; "
                             "--serve-tenants/--serve-models enables "
                             "it"})
                return
            _, tenant, _ = parts
            try:
                body = self._json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            for k in ("qps", "burst"):
                v = body.get(k)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool)
                                      or not math.isfinite(v)):
                    self._send(400, {"error": f"{k!r} must be a finite "
                                              f"number, got {v!r}"})
                    return
            try:
                with admin_lock:
                    cls = scheduler.set_quota(tenant,
                                              qps=body.get("qps"),
                                              burst=body.get("burst"))
                self._send(200, {"tenant": tenant, "qps": cls.qps,
                                 "burst": cls.burst,
                                 "weight": cls.weight,
                                 "deadline_ms": cls.deadline_ms})
            except KeyError as e:
                self._send(404, {"error": str(e)})
            except ValueError as e:
                # SLOClass validation refused the values (e.g. qps<=0)
                self._send(400, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        # -- admin: cluster epoch (ISSUE 19) ---------------------------

        def _cluster_epoch_admin(self):
            """POST /cluster/epoch {"epoch": int} — a gateway's
            promote fan-out landing on this worker. From here on every
            /predict response is stamped X-Cluster-Epoch so the
            gateway can reject any reply computed under a different
            epoch than it admitted the request for; the prediction
            cache's invalidation epoch aligns in the same step."""
            try:
                body = self._json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            epoch = body.get("epoch")
            if (not isinstance(epoch, int) or isinstance(epoch, bool)
                    or epoch < 0):
                self._send(400, {"error": "'epoch' must be an integer "
                                          f">= 0, got {epoch!r}"})
                return
            with admin_lock:
                apply_cluster_epoch(state, cache, epoch)
            self._send(200, {
                "cluster_epoch": epoch,
                "cache": cache.stats() if cache is not None else None})

        # -- admin: model lifecycle -----------------------------------

        def _models_load(self):
            try:
                body = self._json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            # Fresh-init load (ISSUE 19): {"fresh": {"version"?,
            # "seed"?}} registers + pre-warms a fresh-initialized
            # version instead of a checkpoint restore — how a gateway
            # bench stages a promotable second version on every worker
            # of a fleet that shares no trained checkpoint.
            fresh = body.get("fresh")
            if fresh is not None and not isinstance(fresh, dict):
                self._send(400, {"error": "'fresh' must be a JSON "
                                          f"object, got {fresh!r}"})
                return
            if fresh is not None:
                seed = fresh.get("seed", 0)
                if not isinstance(seed, int) or isinstance(seed, bool):
                    self._send(400, {"error": "'fresh.seed' must be an "
                                              f"integer, got {seed!r}"})
                    return
            try:
                # Load + pre-warm runs on THIS handler thread — the
                # dispatch thread keeps serving the live version
                # throughout (warmup is off the hot path by
                # construction).
                with admin_lock:
                    if fresh is not None:
                        mv = registry.add_fresh(
                            version=fresh.get("version"),
                            seed=fresh.get("seed", 0))
                    else:
                        mv = registry.load_latest(
                            directory=body.get("dir"),
                            version=body.get("version"))
                self._send(200, mv.describe())
            except FileNotFoundError as e:
                self._send(404, {"error": str(e)})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except RuntimeError as e:
                # lifecycle conflict (e.g. registry full of route-
                # holding versions): client-resolvable, same 409
                # semantics as promote's rule refusals
                self._send(409, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _models_promote(self):
            try:
                body = self._json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            version = body.get("version")
            mode = body.get("mode", "live")
            if not version:
                self._send(400, {"error": "missing 'version'"})
                return
            if mode not in ("live", "shadow", "canary"):
                self._send(400, {"error": f"unknown mode {mode!r}"})
                return
            # Malformed input is a 400 like the checks above — decided
            # BEFORE the lifecycle try block, whose ValueError arm means
            # "valid request, rules refused it" (409).
            try:
                fraction = float(body.get("fraction", 0.1))
            except (TypeError, ValueError):
                self._send(400, {"error": "'fraction' must be a number, "
                                          f"got {body.get('fraction')!r}"})
                return
            # Optional serving precision for a live promote (ISSUE 7):
            # route one of the version's parity-gated variants instead
            # of the f32 base. Validated against the known dtypes here
            # (400); an unwarmed/refused variant is a rule conflict
            # below (409).
            infer_dtype = body.get("infer_dtype")
            if infer_dtype is not None:
                from distributedmnist_tpu.serve.quantize import \
                    INFER_DTYPES
                if mode != "live":
                    self._send(400, {"error": "'infer_dtype' only "
                                              "applies to mode 'live'"})
                    return
                if infer_dtype not in INFER_DTYPES:
                    self._send(400, {"error": f"unknown infer_dtype "
                                              f"{infer_dtype!r}; one of "
                                              f"{list(INFER_DTYPES)}"})
                    return
            # Optional cascade-threshold override (ISSUE 17): re-gates
            # the version's cascade at this margin BEFORE the swap.
            # Malformed values are 400s here; a well-formed value the
            # composed-accuracy gate refuses (or a version with no
            # cascade) is a rule conflict below (409).
            cascade_threshold = body.get("cascade_threshold")
            if cascade_threshold is not None:
                if mode != "live":
                    self._send(400, {"error": "'cascade_threshold' only "
                                              "applies to mode 'live'"})
                    return
                try:
                    cascade_threshold = float(cascade_threshold)
                except (TypeError, ValueError):
                    self._send(400, {
                        "error": "'cascade_threshold' must be a number, "
                                 f"got {body.get('cascade_threshold')!r}"})
                    return
                if (not math.isfinite(cascade_threshold)
                        or not 0.0 <= cascade_threshold <= 1.0):
                    self._send(400, {
                        "error": "'cascade_threshold' must be a finite "
                                 "number in [0, 1], got "
                                 f"{cascade_threshold!r}"})
                    return
            try:
                with admin_lock:
                    if mode == "live":
                        mv = registry.promote(
                            version, infer_dtype=infer_dtype,
                            cascade_threshold=cascade_threshold)
                    elif mode == "shadow":
                        mv = registry.set_shadow(version, fraction)
                    else:
                        mv = registry.set_canary(version, fraction)
                self._send(200, {"promoted": mv.version, "mode": mode,
                                 **registry.describe()["routes"]})
            except KeyError as e:
                self._send(404, {"error": str(e)})
            except (ValueError, RuntimeError) as e:
                # un-warmed version / bad fraction: a conflict with the
                # lifecycle rules, not a server fault
                self._send(409, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        # -- data path -------------------------------------------------

        def _predict(self):
            length = int(self.headers.get("Content-Length", 0))
            if length == 0 or length % IMAGE_BYTES:
                self._send(400, {"error": "body must be n*784 raw "
                                          "uint8 pixel bytes"})
                return
            if length > max_body:
                self._send(413, {"error": f"at most "
                                          f"{registry.factory.max_batch} "
                                          "images per request"})
                return
            import numpy as np
            # Deadline propagation (ISSUE 5): X-Deadline-Ms is the
            # client's remaining latency budget. It rides the request
            # into the batcher, which sheds it BEFORE dispatch if it
            # expires while queued (504 fast, zero device work) — and
            # bounds this handler's own wait, so the client never
            # learns its answer later than it said it could use it.
            hdr = self.headers.get("X-Deadline-Ms")
            budget_s = deadline_s = None
            if hdr is not None:
                try:
                    budget_s = float(hdr) / 1e3
                except ValueError:
                    self._send(400, {"error": "X-Deadline-Ms must be a "
                                              f"number, got {hdr!r}"})
                    return
                if not math.isfinite(budget_s) or budget_s <= 0:
                    # nan would sail through a bare <= 0 check and
                    # silently disable the deadline — malformed budgets
                    # fail loudly like every other malformed input
                    self._send(400, {"error": "X-Deadline-Ms must be a "
                                              "finite number > 0"})
                    return
                deadline_s = time.monotonic() + budget_s
            # Accuracy class (ISSUE 17): X-Accuracy-Class picks where
            # this request sits on the goodput/accuracy frontier —
            # "fast" = cheap variant only, "balanced" = the confidence
            # cascade, "exact" = f32 reference only. Only meaningful
            # when the cascade front is installed: a class header sent
            # to a non-cascade server is a client config error and must
            # fail loudly (400), never silently serve some other
            # precision than the client asked for.
            acc_hdr = self.headers.get("X-Accuracy-Class")
            accuracy_class = None
            if acc_hdr is not None:
                accuracy_class = acc_hdr.strip().lower()
                if accuracy_class not in ACCURACY_CLASSES:
                    self._send(400, {
                        "error": "X-Accuracy-Class must be one of "
                                 f"{'|'.join(ACCURACY_CLASSES)}, got "
                                 f"{acc_hdr!r}"})
                    return
                if not getattr(submit_to, "is_cascade_front", False):
                    self._send(400, {
                        "error": "X-Accuracy-Class requires the "
                                 "confidence cascade; restart with "
                                 "--serve-cascade"})
                    return
            # Tenant identity (ISSUE 18): X-Tenant names the SLO class
            # this request is admitted under — quota, deadline and
            # weight all follow from it (unknown names fall to the
            # "default" class INSIDE the scheduler, so accounting still
            # sees them). Sent to a single-model server it is a client
            # config error, loud like X-Accuracy-Class above — the
            # client believes it has an SLO contract this server will
            # not honor.
            tenant_hdr = self.headers.get("X-Tenant")
            if tenant_hdr is not None and scheduler is None:
                self._send(400, {
                    "error": "X-Tenant requires multi-tenant serving; "
                             "restart with --serve-tenants"})
                return
            raw = self.rfile.read(length)
            x = np.frombuffer(raw, np.uint8).reshape(-1, IMAGE_BYTES)
            fut = None

            def trace_headers() -> dict:
                """X-Trace-Id on every response whose request entered
                the pipeline (ISSUE 9), plus an opt-in Server-Timing
                stage breakdown (send `X-Server-Timing: 1`) — readable
                because the batcher finishes a trace BEFORE resolving
                its future. Under a gateway (ISSUE 19) every response
                also carries X-Cluster-Epoch (the mixed-epoch tripwire
                reads it) and echoes the gateway's X-Gateway-Trace-Id
                so the two processes' traces name each other."""
                hdrs = {}
                epoch = state.cluster_epoch()
                if epoch is not None:
                    hdrs["X-Cluster-Epoch"] = str(epoch)
                gtid = self.headers.get("X-Gateway-Trace-Id")
                if gtid:
                    hdrs["X-Gateway-Trace-Id"] = gtid
                tid = getattr(fut, "trace_id", None)
                if tid is None:
                    return hdrs
                hdrs["X-Trace-Id"] = tid
                # explicit opt-IN only: "X-Server-Timing: 0" must not
                # enable the breakdown just by being a truthy string
                opt = (self.headers.get("X-Server-Timing") or "")
                if opt.strip().lower() in ("1", "true", "yes", "on"):
                    tracer = trace_lib.active()
                    st = (tracer.server_timing(tid)
                          if tracer is not None else None)
                    if st:
                        hdrs["Server-Timing"] = st
                return hdrs
            try:
                # Bounded wait: if the dispatch pipeline wedges, this
                # handler thread must come back (504) rather than be
                # held forever — ThreadingHTTPServer has no thread cap,
                # so unbounded waiters pile up until exhaustion.
                # submit through the cache front when installed: a hit
                # comes back already resolved (still version-tagged and
                # X-Trace-Id'd), a collapsed miss shares its leader's
                # computation, everything else flows to the batcher
                if scheduler is not None:
                    fut = scheduler.submit(x, tenant=tenant_hdr,
                                           deadline_s=deadline_s)
                elif accuracy_class is not None:
                    fut = submit_to.submit(x, deadline_s=deadline_s,
                                           accuracy_class=accuracy_class)
                else:
                    fut = submit_to.submit(x, deadline_s=deadline_s)
                logits = fut.result(timeout=(
                    request_timeout if budget_s is None
                    else min(request_timeout, budget_s)))
            except QuotaExceeded as e:
                # over the tenant's token bucket (ISSUE 18): 429 with
                # the bucket's own refill time, capped like every other
                # Retry-After this server sends
                self._send(429, {"error": str(e)}, extra={
                    "Retry-After": str(max(1, min(
                        int(math.ceil(e.retry_after_s)),
                        int(retry_after_cap_s))))})
                return
            except Rejected:
                self._send(503, {"error": "overloaded; retry"},
                           extra=retry_after())
                return
            except NoLiveModel:
                # still warming (or drained of versions): same shed
                # semantics as overload — the client should retry, and
                # /healthz says why
                self._send(503, {"error": "no warmed model is live yet"},
                           extra={**retry_after(), **trace_headers()})
                return
            except DeadlineExceeded as e:
                # shed before dispatch: the batcher spent zero device
                # work on this request (or refused it at submit)
                self._send(504, {"error": str(e)},
                           extra={**retry_after(), **trace_headers()})
                return
            except concurrent.futures.TimeoutError:
                if (deadline_s is not None
                        and time.monotonic() >= deadline_s):
                    self._send(504, {"error": "deadline expired while "
                                              "awaiting inference"},
                               extra={**retry_after(),
                                      **trace_headers()})
                else:
                    self._send(504,
                               {"error": "inference timed out after "
                                         f"{request_timeout:g}s"},
                               extra=trace_headers())
                return
            except Exception as e:   # engine fan-out / batcher stopped:
                # an HTTP error beats a dropped keep-alive connection
                self._send(500, {"error": f"{type(e).__name__}: {e}"},
                           extra=trace_headers())
                return
            # The version that COMPUTED this batch (tagged onto the
            # future by the completion thread) — under canary routing
            # that is not necessarily the live version.
            self._send(200, {"classes": logits.argmax(-1).tolist(),
                             "n": int(x.shape[0]),
                             "version": getattr(fut, "version", None)},
                       extra=trace_headers())

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    bound = srv.server_address[1]
    # Announce the port FIRST, then warm: /healthz is pollable (and
    # honestly 503) during model warmup, flipping to 200 only once the
    # initial version is live with every bucket compiled.
    print(json.dumps({"metric": "serve_ready", "port": bound}),
          flush=True)

    def _warm():
        try:
            warm()
            # draining is terminal: a SIGTERM that landed mid-warmup
            # must not be clobbered back to "running" by this thread —
            # the load balancer already saw 503 and moved on.
            state.mark_running()
        except Exception:
            state.mark_failed()
            log.exception("initial model load/warm failed; serving "
                          "503s until an admin load succeeds")

    make_thread(target=_warm, name="serve-warm", daemon=True).start()

    stop = threading.Event()

    def _beat():
        while not stop.wait(metrics_every):
            print(metrics.heartbeat_line(), flush=True)

    beat = make_thread(target=_beat, name="serve-heartbeat", daemon=True)
    beat.start()

    def _shutdown(signum, frame):
        # draining: healthz flips 503 so load balancers stop routing
        # here while in-flight work finishes; shutdown() must come from
        # another thread than serve_forever()
        state.begin_drain()
        make_thread(target=srv.shutdown, name="serve-shutdown",
                    daemon=True).start()

    def _reload(signum, frame):
        # SIGHUP = roll the model: params-only restore of the latest
        # committed checkpoint, pre-warm, atomic promote. Runs on its
        # own thread — signal handlers must not block on a warmup.
        def run():
            try:
                with admin_lock:
                    mv = registry.load_latest()
                    registry.promote(mv.version)
                log.info("SIGHUP reload: %s is live", mv.version)
            except Exception:
                log.exception("SIGHUP reload failed; live version "
                              "unchanged")
                return
            # Re-activate the CONFIGURED precision on the new version
            # (ISSUE 7): a routine checkpoint roll must not silently
            # revert an int8 deployment to the f32 base — the new
            # params re-gate from scratch, and a refusal leaves the new
            # version serving f32 loudly (visible in GET /models).
            if infer_dtype_choice != "float32":
                try:
                    with admin_lock:
                        pick = registry.activate_infer_dtype(
                            mv.version, infer_dtype_choice)
                    log.info("SIGHUP reload: %s serving %s", mv.version,
                             pick)
                except Exception:
                    log.exception(
                        "SIGHUP reload: --serve-infer-dtype %s refused "
                        "on %s; float32 stays live for it",
                        infer_dtype_choice, mv.version)
            # Re-enable the cascade on the new version (ISSUE 17): the
            # new params recalibrate the confidence threshold and
            # re-run the composed-accuracy gate from scratch — a
            # checkpoint roll must never carry a stale threshold
            # forward. A refusal leaves every accuracy class degrading
            # to the plain live route, loudly.
            if cascade:
                try:
                    with admin_lock:
                        st = registry.enable_cascade(
                            mv.version, threshold=cascade_threshold)
                    log.info("SIGHUP reload: cascade re-gated on %s "
                             "(cheap %s, threshold %.4g)", mv.version,
                             st.cheap_dtype, st.threshold)
                except Exception:
                    log.exception(
                        "SIGHUP reload: cascade refused on %s; accuracy "
                        "classes degrade to the plain live route (see "
                        "GET /models for the gate verdict)", mv.version)

        make_thread(target=run, name="serve-reload",
                    daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGHUP, _reload)
    try:
        srv.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        srv.server_close()
    summary = {"metric": "serve_summary", "port": bound,
               "live_version": registry.live_version(),
               **metrics.snapshot()}
    if cache is not None:
        summary["cache"] = cache.stats()
    if scheduler is not None:
        summary["tenancy"] = scheduler.snapshot()
    return summary


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    config_lib.add_args(p)
    p.add_argument("--port", type=int, default=None,
                   help="serve HTTP on this port (0 = ephemeral, "
                        "announced on stdout); omit for selftest mode")
    p.add_argument("--selftest", type=int, default=None, metavar="N",
                   help="run N synthetic requests through the batcher "
                        "and exit (default mode, N=256)")
    p.add_argument("--metrics-every", type=float, default=10.0,
                   help="seconds between serve_stats heartbeat lines")
    p.add_argument("--request-timeout", type=float, default=60.0,
                   help="seconds an HTTP request may wait on its result "
                        "before a 504 (bounds handler-thread lifetime "
                        "when the pipeline wedges)")
    args = p.parse_args(argv)
    if args.port is not None and args.selftest is not None:
        p.error("--port and --selftest are mutually exclusive")
    if args.request_timeout <= 0:
        p.error("--request-timeout must be > 0")
    # Gateway mode (ISSUE 19): this process becomes the fleet front
    # door — it spawns N full serve.py workers and routes, so the
    # in-process single-server modes don't apply to it.
    if args.gateway_workers is not None and args.gateway_workers < 1:
        p.error("--gateway must be >= 1 workers")
    if (args.gateway_worker_inflight is not None
            and args.gateway_worker_inflight < 1):
        p.error("--gateway-worker-inflight must be >= 1")
    if args.gateway_vnodes is not None and args.gateway_vnodes < 1:
        p.error("--gateway-vnodes must be >= 1")
    if args.gateway_workers:
        if args.selftest is not None:
            p.error("--gateway serves HTTP; it does not compose with "
                    "--selftest")
        if args.port is None:
            p.error("--gateway requires --port (0 = ephemeral, "
                    "announced as gateway_ready on stdout)")
    if args.serve_max_inflight is not None and args.serve_max_inflight < 1:
        p.error("--serve-max-inflight must be >= 1")
    if args.serve_max_versions is not None and args.serve_max_versions < 2:
        p.error("--serve-max-versions must be >= 2 (live + a candidate)")
    if args.serve_slo_ms is not None and args.serve_slo_ms <= 0:
        p.error("--serve-slo-ms must be > 0")
    if (args.serve_breaker_window_s is not None
            and args.serve_breaker_window_s <= 0):
        p.error("--serve-breaker-window-s must be > 0")
    if (args.serve_breaker_min_requests is not None
            and args.serve_breaker_min_requests < 1):
        p.error("--serve-breaker-min-requests must be >= 1")
    if (args.serve_breaker_ratio is not None
            and not 0 < args.serve_breaker_ratio <= 1):
        p.error("--serve-breaker-ratio must be in (0, 1]")
    if args.serve_replicas is not None and args.serve_replicas < 1:
        p.error("--serve-replicas must be >= 1")
    if (args.serve_replica_inflight is not None
            and args.serve_replica_inflight < 1):
        p.error("--serve-replica-inflight must be >= 1")
    if (args.serve_retry_after_cap_s is not None
            and args.serve_retry_after_cap_s < 1):
        p.error("--serve-retry-after-cap-s must be >= 1")
    if (args.serve_trace_sample is not None
            and not 0.0 <= args.serve_trace_sample <= 1.0):
        p.error("--serve-trace-sample must be in [0, 1]")
    if (args.serve_trace_capacity is not None
            and args.serve_trace_capacity < 1):
        p.error("--serve-trace-capacity must be >= 1")
    if (args.serve_autoscale_floor is not None
            and args.serve_autoscale_floor < 1):
        p.error("--serve-autoscale-floor must be >= 1")
    if (args.serve_autoscale_ceiling is not None
            and args.serve_autoscale_ceiling < 1):
        p.error("--serve-autoscale-ceiling must be >= 1")
    if (args.serve_autoscale_floor is not None
            and args.serve_autoscale_ceiling is not None
            and args.serve_autoscale_ceiling < args.serve_autoscale_floor):
        p.error("--serve-autoscale-ceiling must be >= "
                "--serve-autoscale-floor")
    if (args.serve_autoscale_interval_s is not None
            and args.serve_autoscale_interval_s <= 0):
        p.error("--serve-autoscale-interval-s must be > 0")
    if (args.serve_autoscale_cooldown_s is not None
            and args.serve_autoscale_cooldown_s < 0):
        p.error("--serve-autoscale-cooldown-s must be >= 0")
    _as_dflt = config_lib.Config()
    _as_high = (args.serve_autoscale_high
                if args.serve_autoscale_high is not None
                else _as_dflt.serve_autoscale_high)
    _as_low = (args.serve_autoscale_low
               if args.serve_autoscale_low is not None
               else _as_dflt.serve_autoscale_low)
    if not 0.0 <= _as_low < _as_high:
        p.error("autoscale hysteresis bands need 0 <= low < high, got "
                f"low={_as_low} high={_as_high}")
    if args.serve_autoscale:
        if args.serve_tenants or args.serve_models:
            p.error("--serve-autoscale does not compose with "
                    "multi-tenant serving yet (the global scheduler "
                    "owns the dispatch surface)")
        if args.gateway_workers:
            p.error("--serve-autoscale under --gateway is not wired "
                    "into the front door yet; the fleet actuator is "
                    "driven through serve/autoscale.GatewayActuator "
                    "(see bench.py serve --trace-replay)")
    if (args.serve_cache_capacity is not None
            and args.serve_cache_capacity < 1):
        p.error("--serve-cache-capacity must be >= 1")
    if (args.serve_cache_ttl_s is not None
            and args.serve_cache_ttl_s <= 0):
        p.error("--serve-cache-ttl-s must be > 0")
    if args.serve_cascade_threshold is not None:
        if not args.serve_cascade:
            p.error("--serve-cascade-threshold requires --serve-cascade")
        if not 0.0 <= args.serve_cascade_threshold <= 1.0:
            # nan fails both comparisons, so it lands here too — a
            # malformed threshold must never silently disable the gate
            p.error("--serve-cascade-threshold must be in [0, 1]")
    if args.serve_tenants or args.serve_models:
        # Multi-tenant mode (ISSUE 18): a malformed SLO-class spec is
        # a usage error NOW — it must never boot a server that
        # silently rate-limits nobody. The single-model fronts don't
        # compose with the global scheduler (it owns every dispatch
        # decision), so their flags are refused loudly instead of
        # silently ignored.
        if args.serve_tenants:
            from distributedmnist_tpu.serve.tenancy import parse_tenants
            try:
                parse_tenants(args.serve_tenants)
            except ValueError as e:
                p.error(f"--serve-tenants: {e}")
        if (args.serve_tenant_quantum_us is not None
                and args.serve_tenant_quantum_us <= 0):
            # a zero/negative quantum would fail deep in the scheduler
            # boot with a traceback; misconfig is a usage error NOW
            p.error("--serve-tenant-quantum-us must be > 0")
        if args.serve_models:
            for name in (s.strip()
                         for s in args.serve_models.split(",")):
                if name not in ("mlp", "lenet"):
                    p.error(f"--serve-models: unknown model {name!r} "
                            "(expected mlp|lenet)")
        if args.serve_cascade:
            p.error("--serve-cascade does not compose with multi-tenant "
                    "serving (the global scheduler owns dispatch)")
        if args.serve_replicas is not None and args.serve_replicas > 1:
            p.error("--serve-replicas does not compose with multi-tenant "
                    "serving yet")
        if args.serve_fastlane:
            p.error("--serve-fastlane does not compose with multi-tenant "
                    "serving (every dispatch is a scheduler grant)")
    if args.serve_faults is not None:
        # a malformed chaos schedule is a usage error NOW — it must
        # never boot a server that silently injects nothing
        from distributedmnist_tpu.serve.faults import parse_spec
        try:
            parse_spec(args.serve_faults)
        except ValueError as e:
            p.error(f"--serve-faults: {e}")
    cfg = config_lib.from_args(args)

    # Gateway mode branches BEFORE any engine import or build: the
    # gateway process routes HTTP and spawns workers — it must never
    # initialize jax or hold device memory itself (the workers own
    # the accelerators; the front door stays a cheap pure-Python
    # process).
    if cfg.gateway_workers:
        from distributedmnist_tpu.serve.gateway import run_gateway
        gw_args = argparse.Namespace(
            gateway_workers=cfg.gateway_workers,
            gateway_worker_inflight=cfg.gateway_worker_inflight,
            gateway_vnodes=cfg.gateway_vnodes,
            serve_cache=cfg.serve_cache,
            serve_trace=cfg.serve_trace,
            serve_trace_capacity=cfg.serve_trace_capacity,
            serve_trace_sample=cfg.serve_trace_sample,
            serve_slo_ms=cfg.serve_slo_ms,
            seed=cfg.seed,
            port=args.port,
            metrics_every=args.metrics_every)
        return run_gateway(
            gw_args, list(sys.argv[1:] if argv is None else argv))

    from distributedmnist_tpu.serve import (DynamicBatcher, ServeMetrics,
                                            build_resilience,
                                            build_serving, faults)

    metrics = ServeMetrics()
    # Multi-tenant, multi-model mode (ISSUE 18): --serve-tenants /
    # --serve-models boots the ModelCatalog + GlobalScheduler stack —
    # one serving pipeline per catalog model, every dispatch decision
    # owned by the weighted-fair, deadline-feasibility scheduler. The
    # single-model path below stays byte-for-byte the compat default.
    tenancy_on = bool(cfg.serve_tenants or cfg.serve_models)
    catalog = scheduler = autoscaler = None
    if tenancy_on:
        from distributedmnist_tpu.serve import build_tenancy
        catalog, scheduler = build_tenancy(cfg, metrics=metrics)
        entry = catalog.get(catalog.default())
        registry, router, factory = (entry.registry, entry.router,
                                     entry.factory)
        batcher = entry.batcher
        log.info("multi-tenant serving ACTIVE: models %s, tenants %s "
                 "(quantum %.1f ms); X-Tenant picks the SLO class, "
                 "GET /tenants shows the scheduler's view",
                 catalog.names(), sorted(scheduler.classes()),
                 cfg.serve_tenant_quantum_us / 1e3)
    else:
        registry, router, factory = build_serving(cfg, metrics=metrics)
    # The resilience policy bundle (ISSUE 5): deadline shedding and
    # bisection live in the batcher; the circuit breaker auto-rolls the
    # live version back through the registry on trip.
    resilience = (build_resilience(cfg, registry=registry,
                                   metrics=metrics)
                  if not tenancy_on else None)
    if cfg.serve_faults:
        faults.install(faults.FaultInjector.from_spec(cfg.serve_faults,
                                                      seed=cfg.seed))
        log.warning("FAULT INJECTION ACTIVE (--serve-faults %r, seed "
                    "%d) — this process is a chaos target, not a "
                    "production server", cfg.serve_faults, cfg.seed)
    if cfg.serve_trace:
        from distributedmnist_tpu.serve import trace as trace_lib
        trace_lib.install(trace_lib.Tracer(
            capacity=cfg.serve_trace_capacity,
            sample=cfg.serve_trace_sample,
            slo_ms=cfg.serve_slo_ms, seed=cfg.seed))
        log.info("request tracing ACTIVE (capacity %d, sample %.2f, "
                 "slo %s ms): GET /trace exports Chrome trace-event "
                 "JSON; /predict responses carry X-Trace-Id",
                 cfg.serve_trace_capacity, cfg.serve_trace_sample,
                 cfg.serve_slo_ms)
    if tenancy_on:
        # Every submit flows through the scheduler; the default
        # model's cache (if any) still backs the cache-aware shed
        # inside admission, and per-model fronts live in the catalog.
        front, cache = scheduler, catalog.get(catalog.default()).cache
    else:
        batcher = DynamicBatcher(router, max_batch=cfg.serve_max_batch,
                                 max_wait_us=cfg.serve_max_wait_us,
                                 queue_depth=cfg.serve_queue_depth,
                                 max_inflight=cfg.serve_max_inflight,
                                 slo_ms=cfg.serve_slo_ms,
                                 adaptive=cfg.serve_adaptive,
                                 resilience=resilience,
                                 dedup=cfg.serve_dedup,
                                 fastlane=cfg.serve_fastlane,
                                 metrics=metrics).start()
        if cfg.serve_fastlane:
            log.info("single-request fast lane ACTIVE: an idle pipeline "
                     "dispatches lone requests on the caller's thread "
                     "(no coalesce wait); contention falls back to "
                     "coalescing")
        # The prediction cache + single-flight front layer (ISSUE 10):
        # front is the submit target (== batcher when --serve-cache is
        # off); the registry invalidates the cache atomically on every
        # live-route change via the set_cache hook build_cache_front
        # installs.
        from distributedmnist_tpu.serve import build_cache_front
        front, cache = build_cache_front(cfg, batcher, router, registry,
                                         metrics=metrics)
        if cache is not None:
            log.info("prediction cache ACTIVE (capacity %d entries, "
                     "dedup %s): hits skip the pipeline, identical "
                     "concurrent misses collapse",
                     cfg.serve_cache_capacity,
                     "on" if cfg.serve_dedup else "off")
        # The confidence-gated cascade front (ISSUE 17): wraps the
        # submit target so per-request accuracy classes route through
        # the cheap variant + escalation machinery. Wrapping is
        # unconditional under --serve-cascade — until warm()
        # calibrates and gates the cascade, the front degrades every
        # class to the plain live route (metered as degraded, never an
        # error).
        if cfg.serve_cascade:
            from distributedmnist_tpu.serve.cascade import CascadeFront
            front = CascadeFront(front, batcher, router, registry,
                                 metrics=metrics, cache=cache)
            log.info("confidence cascade REQUESTED: calibration + the "
                     "composed-accuracy gate run at warmup; X-Accuracy-"
                     "Class picks fast|balanced|exact per request")
        # Closed-loop autoscaling (ISSUE 20): the window actuator over
        # THIS batcher, fed by the live saturation surface. Built after
        # every front wrapper — the loop reads/actuates the batcher
        # directly, never the submit path.
        if cfg.serve_autoscale:
            from distributedmnist_tpu.serve import (
                autoscale as autoscale_lib)
            as_ceiling = (cfg.serve_autoscale_ceiling
                          if cfg.serve_autoscale_ceiling is not None
                          else batcher.max_inflight)
            actuator = autoscale_lib.WindowActuator(
                batcher, floor=cfg.serve_autoscale_floor,
                ceiling=as_ceiling)
            from distributedmnist_tpu.serve import trace as _trace_mod
            autoscaler = autoscale_lib.Autoscaler(
                actuator,
                autoscale_lib.batcher_signals(
                    batcher, metrics=metrics, slo_ms=cfg.serve_slo_ms,
                    tracer=_trace_mod.active()),
                high=cfg.serve_autoscale_high,
                low=cfg.serve_autoscale_low,
                cooldown_s=cfg.serve_autoscale_cooldown_s,
                interval_s=cfg.serve_autoscale_interval_s,
                metrics=metrics).start()
            log.info("autoscaler ACTIVE (window actuator): floor %d "
                     "ceiling %d, bands [%.2f, %.2f], cooldown %.1fs, "
                     "tick %.2fs — scale moves only along the warmed "
                     "bucket ladder (zero recompiles)",
                     autoscaler.floor, autoscaler.ceiling,
                     autoscaler.low, autoscaler.high,
                     autoscaler.cooldown_s, autoscaler.interval_s)
    log.info("dispatch pipeline depth: %d; buckets %s",
             batcher.max_inflight, list(factory.buckets))
    state = ServerState()

    def warm():
        if tenancy_on:
            # Eager residency for every catalog model: the scheduler
            # can warm lazily (a priced event on first backlog), but a
            # server boot warms the whole catalog so /healthz's 200
            # means EVERY advertised model answers with zero
            # steady-state recompiles.
            t0 = time.perf_counter()
            for name in catalog.names():
                catalog.ensure_live(name, seed=cfg.seed,
                                    infer_dtype=cfg.serve_infer_dtype)
            log.info("catalog warmed in %.2fs: %s",
                     time.perf_counter() - t0, catalog.describe())
            return
        t0 = time.perf_counter()
        mv = registry.bootstrap(seed=cfg.seed)
        log.info("bootstrap %s (%s) warmed in %.2fs — %d compile "
                 "events; live: %s", mv.version, mv.source,
                 time.perf_counter() - t0, mv.warmup_compile_events,
                 registry.live_version())
        # The inference fast path (ISSUE 7): f32 is live and serving
        # already; warming + parity-gating the requested low-precision
        # variant(s) happens ON TOP, and the promote only lands if the
        # gate passed. A refused variant leaves f32 serving — the
        # refusal is loud here and visible per-variant in GET /models.
        if cfg.serve_infer_dtype != "float32":
            try:
                pick = registry.activate_infer_dtype(
                    mv.version, cfg.serve_infer_dtype)
                log.info("inference fast path: %s is live (%s)", pick,
                         "auto-picked" if cfg.serve_infer_dtype == "auto"
                         else "requested")
            except Exception:
                log.exception(
                    "--serve-infer-dtype %s refused; float32 stays "
                    "live (see GET /models variants for the parity "
                    "verdict)", cfg.serve_infer_dtype)
        # Calibrate + gate the cascade (ISSUE 17): builds the cheap
        # variant if needed, calibrates the confidence threshold on
        # the held-out parity batch and runs the END-TO-END composed-
        # accuracy gate. A refusal leaves the plain live route serving
        # every accuracy class — loud here, verdict in GET /models.
        if cfg.serve_cascade:
            try:
                st = registry.enable_cascade(
                    mv.version, threshold=cfg.serve_cascade_threshold)
                log.info("confidence cascade ACTIVE on %s: cheap stage "
                         "%s, threshold %.4g (%s)", mv.version,
                         st.cheap_dtype, st.threshold,
                         st.calibration.get("source", "calibrated"))
            except Exception:
                log.exception(
                    "--serve-cascade refused on %s; every accuracy "
                    "class serves the plain live route (see GET "
                    "/models for the gate verdict)", mv.version)

    try:
        if args.port is None:
            warm()                       # synchronous: the gate is cheap
            state.mark_running()
            summary = _selftest(front, metrics, args.selftest or 256,
                                factory.max_batch)
            if cache is not None:
                summary["cache"] = cache.stats()
            if autoscaler is not None:
                summary["autoscale"] = autoscaler.describe()
        else:
            summary = _http_serve(batcher, metrics, registry, state,
                                  args.port, args.metrics_every,
                                  args.request_timeout, warm,
                                  retry_after_cap_s=(
                                      cfg.serve_retry_after_cap_s),
                                  infer_dtype_choice=(
                                      cfg.serve_infer_dtype),
                                  front=front, cache=cache,
                                  cascade=cfg.serve_cascade,
                                  cascade_threshold=(
                                      cfg.serve_cascade_threshold),
                                  scheduler=scheduler)
    finally:
        # The autoscaler stops BEFORE the batcher: batcher.stop()
        # releases any window permits the actuator parked, and a live
        # control loop could re-park them mid-drain.
        if autoscaler is not None:
            autoscaler.stop()
        if scheduler is not None:
            scheduler.stop()    # drains every per-model batcher too
        else:
            batcher.stop()
    # Sanitizer verdict AFTER stop() (DMNIST_SANITIZE=1 runs): a
    # mid-drain dispatch cycle legitimately holds a window slot while
    # its batch is popped-but-unresolved — "slots net zero" is only a
    # valid invariant once the pipeline is actually stopped, so a
    # snapshot taken mid-serve would flakily report that hold as a
    # leak. (The idle pipeline itself holds no slot since ISSUE 14:
    # the dispatch thread claims one only once there is work, which is
    # what lets the fast lane's try-acquire succeed at depth 1.)
    summary.update(_sanitizer_block())
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
