#!/usr/bin/env python
"""serve.py — request-shaped inference entrypoint over the batched
serving engine (distributedmnist_tpu/serve/), the forward-only sibling
of train.py.

Two modes:

- selftest (default): drive --selftest N synthetic requests of mixed
  sizes through the dynamic batcher in-process, then print one JSON
  summary line ({"metric": "serve_selftest", ...}) — the cheap
  end-to-end gate, and what `python serve.py` does out of the box.
- --port P: serve HTTP on P (0 picks an ephemeral port, announced as a
  {"metric": "serve_ready", "port": ...} JSON line on stdout). stdlib
  http.server only — the container installs nothing.

    POST /predict   body = raw uint8 pixels, n*784 bytes ->
                    {"classes": [...], "n": n}
                    503 + Retry-After when the queue is past its
                    backpressure watermark (shed, don't melt)
    GET  /metrics   current ServeMetrics snapshot (JSON)
    GET  /healthz   {"ok": true}

Periodic {"metric": "serve_stats", ...} heartbeat lines go to stdout
(--metrics-every), so utils/supervise.py's json_record_acceptor can
watch a serving process exactly as it watches the bench. SIGTERM/SIGINT
shut the server down cleanly and print a final summary line.

Model/params come from Config: --checkpoint-dir restores trained params
(the usual serving case); otherwise params are fresh-init (load tests).
Batching knobs: --serve-max-batch, --serve-max-wait-us,
--serve-queue-depth, --serve-max-inflight (config.py). --request-timeout
bounds how long an HTTP client thread may wait on its future before a
504 — a wedged dispatch pipeline must shed its waiters, not hold
ThreadingHTTPServer threads forever.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time

from distributedmnist_tpu import config as config_lib

IMAGE_BYTES = 28 * 28


def _selftest(batcher, metrics, n_requests: int, max_batch: int) -> dict:
    import numpy as np

    from distributedmnist_tpu.serve import Rejected

    rng = np.random.default_rng(0)
    sizes = [int(rng.integers(1, max(2, min(max_batch, 32))))
             for _ in range(n_requests)]
    futures = []
    rejected = 0
    for n in sizes:
        x = rng.integers(0, 256, (n, IMAGE_BYTES), dtype=np.uint8)
        try:
            futures.append((n, batcher.submit(x)))
        except Rejected:
            rejected += 1
    for n, f in futures:
        out = f.result(timeout=120)
        assert out.shape == (n, 10), (out.shape, n)
    return {"metric": "serve_selftest", "requests_driven": n_requests,
            "rejected_at_submit": rejected, **metrics.snapshot()}


def _http_serve(batcher, metrics, engine, port: int,
                metrics_every: float, request_timeout: float) -> dict:
    import concurrent.futures
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from distributedmnist_tpu.serve import Rejected

    max_body = engine.max_batch * IMAGE_BYTES

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # requests are metered, not
            pass                             # per-line logged

        def _send(self, code: int, payload: dict,
                  extra: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"ok": True})
            elif self.path == "/metrics":
                self._send(200, metrics.record())
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path != "/predict":
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            if length == 0 or length % IMAGE_BYTES:
                self._send(400, {"error": "body must be n*784 raw "
                                          "uint8 pixel bytes"})
                return
            if length > max_body:
                self._send(413, {"error": f"at most {engine.max_batch} "
                                          "images per request"})
                return
            import numpy as np
            raw = self.rfile.read(length)
            x = np.frombuffer(raw, np.uint8).reshape(-1, IMAGE_BYTES)
            try:
                # Bounded wait: if the dispatch pipeline wedges, this
                # handler thread must come back (504) rather than be
                # held forever — ThreadingHTTPServer has no thread cap,
                # so unbounded waiters pile up until exhaustion.
                logits = batcher.submit(x).result(timeout=request_timeout)
            except Rejected:
                self._send(503, {"error": "overloaded; retry"},
                           extra={"Retry-After": "1"})
                return
            except concurrent.futures.TimeoutError:
                self._send(504, {"error": "inference timed out after "
                                          f"{request_timeout:g}s"})
                return
            except Exception as e:   # engine fan-out / batcher stopped:
                # an HTTP error beats a dropped keep-alive connection
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, {"classes": logits.argmax(-1).tolist(),
                             "n": int(x.shape[0])})

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    bound = srv.server_address[1]
    print(json.dumps({"metric": "serve_ready", "port": bound}),
          flush=True)

    stop = threading.Event()

    def _beat():
        while not stop.wait(metrics_every):
            print(metrics.heartbeat_line(), flush=True)

    beat = threading.Thread(target=_beat, daemon=True)
    beat.start()

    def _shutdown(signum, frame):
        # shutdown() must come from another thread than serve_forever()
        threading.Thread(target=srv.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        srv.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        srv.server_close()
    return {"metric": "serve_summary", "port": bound,
            **metrics.snapshot()}


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    config_lib.add_args(p)
    p.add_argument("--port", type=int, default=None,
                   help="serve HTTP on this port (0 = ephemeral, "
                        "announced on stdout); omit for selftest mode")
    p.add_argument("--selftest", type=int, default=None, metavar="N",
                   help="run N synthetic requests through the batcher "
                        "and exit (default mode, N=256)")
    p.add_argument("--metrics-every", type=float, default=10.0,
                   help="seconds between serve_stats heartbeat lines")
    p.add_argument("--request-timeout", type=float, default=60.0,
                   help="seconds an HTTP request may wait on its result "
                        "before a 504 (bounds handler-thread lifetime "
                        "when the pipeline wedges)")
    args = p.parse_args(argv)
    if args.port is not None and args.selftest is not None:
        p.error("--port and --selftest are mutually exclusive")
    if args.request_timeout <= 0:
        p.error("--request-timeout must be > 0")
    if args.serve_max_inflight is not None and args.serve_max_inflight < 1:
        p.error("--serve-max-inflight must be >= 1")
    cfg = config_lib.from_args(args)

    from distributedmnist_tpu.serve import (DynamicBatcher, ServeMetrics,
                                            build_engine)

    engine = build_engine(cfg)
    t0 = time.perf_counter()
    engine.warmup()
    logging.getLogger("distributedmnist_tpu").info(
        "buckets %s warm in %.2fs", list(engine.buckets),
        time.perf_counter() - t0)
    metrics = ServeMetrics()
    batcher = DynamicBatcher(engine, max_batch=cfg.serve_max_batch,
                             max_wait_us=cfg.serve_max_wait_us,
                             queue_depth=cfg.serve_queue_depth,
                             max_inflight=cfg.serve_max_inflight,
                             metrics=metrics).start()
    logging.getLogger("distributedmnist_tpu").info(
        "dispatch pipeline depth: %d", batcher.max_inflight)
    try:
        if args.port is None:
            summary = _selftest(batcher, metrics, args.selftest or 256,
                                engine.max_batch)
        else:
            summary = _http_serve(batcher, metrics, engine, args.port,
                                  args.metrics_every,
                                  args.request_timeout)
    finally:
        batcher.stop()
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
