"""distributedmnist_tpu — a TPU-native (JAX/XLA) re-design of the capabilities
of the reference repo `stsievert/DistributedMNIST`.

The reference (per /root/repo/BASELINE.json — the reference mount is empty, so
all parity claims cite BASELINE.json fields rather than file:line; see
SURVEY.md §0) is an NCCL-based data-parallel MNIST trainer:

- two models: 2-layer MLP (784-128-10) and LeNet-5  [BASELINE.json configs 1-2]
- two optimizers: SGD and Adam                       [configs 1-2]
- data parallelism via per-step NCCL gradient allreduce [north_star, configs 3-4]
- shard-by-rank DataLoader                           [north_star]
- async checkpoint/restore                           [config 5]
- metric: MNIST images/sec/chip; wall-clock to 99% test accuracy [metric]

This package is NOT a port. The TPU-native design:

- the forward/backward/allreduce/update is ONE fused XLA program under
  `jax.jit` — the gradient reduction is a `lax.psum`/XLA collective over a
  named ICI mesh axis *inside* the compiled step, not a separate
  post-backward NCCL call;
- the dataset lives device-resident (uint8) and batches are gathered on
  device by a jitted index lookup, so the input pipeline can never starve a
  ~100µs TPU step;
- multi-host scale uses `jax.distributed.initialize` + per-process batch
  assembly (`jax.make_array_from_process_local_data`) — collectives ride
  ICI within a host and DCN across hosts, both inserted by XLA.
"""

__version__ = "0.1.0"

from distributedmnist_tpu.config import Config, PRESETS  # noqa: F401
