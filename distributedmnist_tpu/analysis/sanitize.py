"""Runtime concurrency sanitizer (ISSUE 8): lock-order graph, blocking-
under-lock detection, and resource-balance accounting for the serving
stack.

Production pays nothing: the module-global active sanitizer is None by
default (the serve/faults.py idiom), every hook is one attribute read +
None test, and the lock factories in analysis/locks.py hand back bare
threading primitives while nothing is installed. Installed (via
install_sanitizer() or DMNIST_SANITIZE=1 at import), three checks run
continuously:

1. **Lock-order cycles.** Every sanitized lock acquisition records
   "held -> acquired" edges (by lock NAME — the class-level order is
   the invariant, instances of one name are one node) into a global
   digraph; a new edge that closes a cycle is a potential deadlock
   (thread 1 takes A then B while thread 2 takes B then A), recorded
   with the full path. Nesting two same-named locks on one thread is
   reported as a cycle too: with no defined order within the class,
   two threads nesting opposite instances deadlock the same way.

2. **Blocking under a hot lock.** time.sleep and socket connect/send/
   recv are patched while installed, and engine.fetch's device->host
   value sync calls the blocking() hook directly: any of these on a
   thread holding a sanitized lock not marked blocking_ok is recorded
   (the PR 3 bug class — warmup's multi-second compile under the
   registry state lock starved /healthz — generalized). Slow-by-design
   locks (the registry admin RLock, serve.py's admin lock) opt out
   with make_lock(..., blocking_ok=True).

3. **Resource balance.** Named counters fed by resource_acquire/
   resource_release: the engine's staging-pool checkout/recycle and
   the batcher's in-flight window semaphore must net to zero once the
   pipeline drains (the PR 5 try/finally leak class — a fetch-failure
   storm bleeding one pooled buffer per failed batch — asserted
   automatically at the end of every serve test). A counter going
   negative (release without acquire) is recorded immediately.

Findings are RECORDED, not raised at the detection site: raising inside
someone else's acquire() would corrupt the very pipeline under test.
The conftest autouse fixture calls report()/assert_clean() after each
serve test and fails the test on any finding.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional


class Sanitizer:
    """One installed sanitizer: the lock-order graph, the finding lists,
    the resource counters, and the per-thread held-lock stacks. All
    internal state is guarded by a single raw mutex (never a sanitized
    lock — the sanitizer must not observe itself)."""

    def __init__(self):
        self._mutex = threading.Lock()
        self._tls = threading.local()
        # name -> set of names acquired while holding it (the order
        # digraph); edges, cycles and findings dedupe on stable keys so
        # a hot loop cannot flood the report.
        self._order: dict[str, set] = {}
        self._cycles: list[dict] = []
        self._cycle_keys: set = set()
        self._blocking: list[dict] = []
        self._blocking_keys: set = set()
        self._resources: dict[str, int] = {}
        self._resource_errors: list[dict] = []
        self._threads: list = []       # make_thread-registered threads

    # -- per-thread held stack ---------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held_locks(self) -> list:
        """Names of sanitized locks the CURRENT thread holds, outermost
        first (diagnostics and tests)."""
        return [name for (_, name, _) in self._stack()]

    # -- lock hooks (called by analysis/locks.py wrappers) -----------------

    def on_acquired(self, name: str, obj_id: int,
                    blocking_ok: bool) -> None:
        st = self._stack()
        held = list(st)
        st.append((obj_id, name, blocking_ok))
        if not held:
            return
        thread = threading.current_thread().name
        with self._mutex:
            for hid, hname, _ in held:
                if hid == obj_id:
                    continue          # re-entrant hold of one instance
                self._add_edge_locked(hname, name, thread)

    def on_released(self, name: str, obj_id: int) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == obj_id:
                del st[i]
                return
        # A release with no recorded acquire happens when the lock was
        # taken before install (or by Condition internals): not a
        # finding — the sanitizer only reasons about what it saw.

    def _add_edge_locked(self, a: str, b: str, thread: str) -> None:
        if a == b:
            key = ("same-name", a)
            if key not in self._cycle_keys:
                self._cycle_keys.add(key)
                self._cycles.append({
                    "cycle": [a, a],
                    "thread": thread,
                    "detail": (f"two locks named {a!r} nested on one "
                               "thread: no order is defined within the "
                               "class, so two threads nesting opposite "
                               "instances deadlock (AB/BA)")})
            return
        succ = self._order.setdefault(a, set())
        if b in succ:
            return
        succ.add(b)
        path = self._path_locked(b, a)
        if path is not None:
            cycle = [a] + path        # a -> b -> ... -> a (path ends at a)
            key = frozenset(cycle)
            if key not in self._cycle_keys:
                self._cycle_keys.add(key)
                self._cycles.append({
                    "cycle": cycle,
                    "thread": thread,
                    "detail": ("lock-order cycle (potential deadlock): "
                               + " -> ".join(cycle))})

    def _path_locked(self, src: str, dst: str) -> Optional[list]:
        """A path src -> ... -> dst in the order digraph, or None."""
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._order.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- blocking-call detection -------------------------------------------

    def on_blocking(self, kind: str) -> None:
        hot = [name for (_, name, ok) in self._stack() if not ok]
        if not hot:
            return
        key = (kind, tuple(hot))
        with self._mutex:
            if key in self._blocking_keys:
                return
            self._blocking_keys.add(key)
            self._blocking.append({
                "kind": kind,
                "locks": hot,
                "thread": threading.current_thread().name,
                "detail": (f"blocking call {kind!r} while holding "
                           f"hot-path lock(s) {hot} — move the slow "
                           "work outside the lock (the PR 3 "
                           "warmup-under-state-lock class)")})

    # -- resource balance --------------------------------------------------

    def on_resource(self, name: str, delta: int) -> None:
        # A negative balance is reported unconditionally: within one
        # sanitizer's lifetime a release-without-acquire is always a
        # double-release bug. (One known benign shape: a straggler
        # daemon thread from a PREVIOUS test draining its last fetch
        # against the next test's fresh sanitizer — but that can only
        # happen after the previous test already failed its own drain
        # assert, so the cascade never masks a green run.)
        with self._mutex:
            value = self._resources.get(name, 0) + delta
            self._resources[name] = value
            if value < 0:
                self._resource_errors.append({
                    "resource": name,
                    "balance": value,
                    "thread": threading.current_thread().name,
                    "detail": (f"resource {name!r} released more times "
                               "than acquired (balance went negative)")})

    def balances(self) -> dict:
        """Current net acquire-release count per resource. Every entry
        must be zero once the pipeline is drained — nonzero at drain is
        the PR 5 leak class (a checked-out staging buffer or held
        window slot that no error path returns)."""
        with self._mutex:
            return dict(self._resources)

    def wait_drained(self, timeout_s: float = 5.0,
                     poll_s: float = 0.02) -> bool:
        """Poll until every resource balance reads zero — the caller's
        last future resolves BEFORE the completion/drain daemon threads
        release their slots and recycle their buffers, so an immediate
        snapshot can read a transient +1 as a leak. Returns True once
        drained, False at the deadline (the one grace loop serve.py's
        summary block, the conftest fixture, and tests all share)."""
        deadline = time.monotonic() + timeout_s
        while any(self.balances().values()):
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    # -- thread registry ---------------------------------------------------

    def register_thread(self, t: threading.Thread) -> None:
        with self._mutex:
            # Prune completed threads as we go: a long-lived sanitized
            # serve process spawns short-lived hedge/drain threads
            # continuously, and an append-only list would hold every
            # dead Thread object for the process lifetime.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def leaked_threads(self) -> list:
        """make_thread-spawned NON-daemon threads still alive — the
        leak class the conftest thread-hygiene fixture fails on,
        visible to the sanitizer's own report too."""
        with self._mutex:
            return [t for t in self._threads
                    if t.is_alive() and not t.daemon]

    # -- reporting ---------------------------------------------------------

    def cycles(self) -> list:
        with self._mutex:
            return list(self._cycles)

    def blocking_findings(self) -> list:
        with self._mutex:
            return list(self._blocking)

    def resource_errors(self) -> list:
        with self._mutex:
            return list(self._resource_errors)

    def report(self) -> dict:
        with self._mutex:
            return {
                "cycles": list(self._cycles),
                "blocking": list(self._blocking),
                "resource_errors": list(self._resource_errors),
                "balances": {k: v for k, v in self._resources.items()
                             if v},
                "leaked_threads": [t.name for t in self._threads
                                   if t.is_alive() and not t.daemon],
            }

    def assert_clean(self, artifact=None) -> None:
        """Raise AssertionError naming every finding (cycle paths,
        blocking sites, nonzero balances). The drain contract: call
        only after the pipeline has stopped.

        `artifact` opts into the ANALYSIS_r*.json trajectory (ISSUE
        11): True emits a round record to the repo root, a string
        emits into that directory; DMNIST_ANALYSIS_ARTIFACT=1 turns it
        on without a code change (serve.py's summary verdict). The
        record is written whether the verdict is clean or not — a
        clean round is a data point too, exactly like a BENCH run."""
        rep = self.report()
        if artifact is None and os.environ.get(
                "DMNIST_ANALYSIS_ARTIFACT", "").lower() in (
                "1", "true", "on", "yes"):
            artifact = True
        if artifact:
            from distributedmnist_tpu.analysis import report as report_mod

            root = artifact if isinstance(artifact, str) else None
            report_mod.emit_analysis(
                {"kind": "sanitizer",
                 "clean": not (rep["cycles"] or rep["blocking"]
                               or rep["resource_errors"]
                               or rep["balances"]
                               or rep["leaked_threads"]),
                 "report": rep}, root=root)
        problems = []
        for c in rep["cycles"]:
            problems.append(f"lock-order cycle: {c['detail']}")
        for b in rep["blocking"]:
            problems.append(f"blocking under lock: {b['detail']}")
        for e in rep["resource_errors"]:
            problems.append(f"resource error: {e['detail']}")
        for name, v in rep["balances"].items():
            problems.append(
                f"resource imbalance at drain: {name!r} nets {v:+d} "
                "(leaked checkout or unreleased slot)")
        if rep["leaked_threads"]:
            problems.append(
                f"leaked non-daemon thread(s): {rep['leaked_threads']}")
        if problems:
            raise AssertionError(
                "concurrency sanitizer findings:\n  "
                + "\n  ".join(problems))


# The module-global active sanitizer. None (the default, every
# production process) keeps all the woven hooks to one attribute read.
_active: Optional[Sanitizer] = None

# Patch bookkeeping: (original, our wrapper) per patched callable.
# Uninstall restores ONLY if the current value is still our wrapper —
# another layer (pytest monkeypatch, a test stub) patching over us must
# not be clobbered by a blind restore. A skipped restore is safe: every
# wrapper captures its original in its closure and goes inert (one
# None-check) once no sanitizer is active.
_patched_sleep = None     # (original, wrapper) | None
_patched_socket = {}      # attr -> (original, wrapper)


def active_sanitizer() -> Optional[Sanitizer]:
    return _active


def install_sanitizer() -> Sanitizer:
    """Activate a fresh Sanitizer process-wide and patch time.sleep +
    socket connect/sendall/recv with held-lock checks. Refuses to
    stack (two half-reports would make neither trustworthy)."""
    global _active, _patched_sleep
    if _active is not None:
        raise RuntimeError(
            "a Sanitizer is already installed; uninstall_sanitizer() "
            "first")
    san = Sanitizer()
    _active = san

    real_sleep = time.sleep

    def _checked_sleep(seconds):
        s = _active
        if s is not None:
            # Stable kind — findings dedupe on (kind, held locks), and
            # a backoff loop sleeping computed durations must collapse
            # to ONE finding, not flood the report with one per value.
            s.on_blocking("time.sleep")
        return real_sleep(seconds)

    _patched_sleep = (real_sleep, _checked_sleep)
    time.sleep = _checked_sleep

    def _patch_sock(attr):
        real = getattr(socket.socket, attr)

        def checked(self, *args, **kwargs):
            s = _active
            if s is not None:
                s.on_blocking(f"socket.{attr}")
            return real(self, *args, **kwargs)

        _patched_socket[attr] = (real, checked)
        setattr(socket.socket, attr, checked)

    for attr in ("connect", "sendall", "recv"):
        _patch_sock(attr)
    return san


def uninstall_sanitizer() -> None:
    """Deactivate and restore the patched calls — but only where the
    current value is still OUR wrapper (another layer's later patch
    must survive; our wrapper under it is inert once _active is None).
    Locks created while installed keep their wrappers but go inert the
    same way (every hook re-checks the active sanitizer per call)."""
    global _active, _patched_sleep
    _active = None
    if _patched_sleep is not None:
        real, wrapper = _patched_sleep
        if time.sleep is wrapper:
            time.sleep = real
        _patched_sleep = None
    for attr, (real, wrapper) in list(_patched_socket.items()):
        if getattr(socket.socket, attr, None) is wrapper:
            setattr(socket.socket, attr, real)
    _patched_socket.clear()


# -- the woven hooks (all one None-check when uninstalled) ----------------

def blocking(kind: str) -> None:
    """Declare the caller is about to block (device->host sync, I/O):
    a finding if any hot-path sanitized lock is held on this thread."""
    s = _active
    if s is not None:
        s.on_blocking(kind)


def resource_acquire(name: str) -> None:
    """One unit of `name` checked out (staging buffer taken, window
    slot claimed). Must be matched by resource_release before drain."""
    s = _active
    if s is not None:
        s.on_resource(name, +1)


def resource_release(name: str) -> None:
    s = _active
    if s is not None:
        s.on_resource(name, -1)


# Env-var opt-in (the "turn it on for this serve.py run" path — no code
# change needed): DMNIST_SANITIZE=1 installs at first import, which
# precedes every make_lock call since the factories import this module.
if os.environ.get("DMNIST_SANITIZE", "").lower() in ("1", "true", "on",
                                                     "yes"):
    install_sanitizer()
