"""Model-checking harnesses for the four riskiest serve state machines
(ISSUE 11), driven by `analysis/explore.py`.

Each machine is a small closed world: the REAL serve component under
test (PredictionCache + CacheFront, ModelRegistry + Router,
DynamicBatcher, ReplicaSet) over pure-Python fakes for everything
below it (no device work, no jit — schedules must be cheap and
deterministic), plus client/admin threads that drive the racy
operations and invariants checked at every quiescent step and at
drain. Every primitive the real components build goes through the
`analysis/locks.py` factories, so under an installed Controller the
whole machine is explorable with zero changes to the code under test.

The invariants are the machines' documented contracts:

- **cache**: no stale bytes surface (every cache entry's payload was
  computed in the route era that is still current for its version — an
  insert that crossed an invalidation must have been refused), no
  future left unresolved, the single-flight table empty at drain, and
  every resolved result's payload matches its future's version tag.
- **registry**: at quiescence exactly one version is 'live' and it is
  the router's live target; no routed version is ever evicted.
- **batcher**: every accepted future resolves, pending rows and the
  in-flight gauge return to zero, and the window semaphore's net
  acquire-release balance is zero at drain.
- **fleet**: no mixed-version pick window (all replicas agree on the
  live version whenever the pick lock is free), per-replica windows
  and outstanding cost return to zero, and replica faults are absorbed
  by failover (clients see results, not errors).

Planted mutations (the explorer's self-test — an explorer that cannot
find planted bugs is theater):

- ``mutation="skip-follower"`` drops the first single-flight follower
  registration (the ISSUE 10 "can a follower be skipped?" race, made
  real): the skipped follower's future never resolves, which the
  explorer reports as a deadlock/unresolved-future finding.
- ``mutation="drop-epoch-bump"`` makes invalidation clear entries
  without bumping the epoch: a leader that raced a promote/rollback
  pair lands stale bytes in the cache, violating the era invariant.
"""

from __future__ import annotations

import itertools
import types
from concurrent.futures import Future

import numpy as np

from distributedmnist_tpu.analysis.locks import make_fifo, make_lock

# The tier-1 smoke budget (scripts/tier1.sh runs the CLI with --smoke):
# fixed seeds, this many schedules per machine — small enough to stay
# well under 30 s total, large enough to cross every interesting
# interleaving class at least once per run.
SMOKE_SCHEDULES = 25


def await_future(ctl, fut: Future, what: str = "future") -> None:
    """Cooperative future wait: a controller yield point parked on
    fut.done() — never fut.result() on an unresolved future, which
    would block the real thread outside the controller's model."""
    ctl.yield_point("future.wait", what, ready=fut.done)


def encode(version: str, era: int, rows: int) -> np.ndarray:
    """Version+era-stamped payload: logits whose every element encodes
    (version, route era) so stale bytes are OBSERVABLE, not just
    theorized — the harness twin of the bench's version-encoded-logits
    trick."""
    code = float(int(version.lstrip("v")) * 1000 + era)
    return np.full((rows, 10), code, dtype=np.float32)


def decode(arr: np.ndarray) -> tuple:
    code = int(arr.flat[0])
    return (f"v{code // 1000}", code % 1000)


# -- machine 1: cache single-flight vs promote/invalidation epoch ----------


class _Route:
    """Fake live route + promote: the (version, era) pair the cache
    keys on, mutated atomically with the cache invalidation under one
    state lock — the registry's `_route_set("live", ...)` shape. `era`
    increments on every promote (including re-promotes of an old
    version), so payload bytes can prove WHICH reign computed them."""

    def __init__(self):
        self._state_lock = make_lock("harness.route.state")
        self.version = "v1"
        self.era = 1
        self.era_of = {"v1": 1}

    @staticmethod
    def _as_images(x) -> np.ndarray:
        return np.asarray(x, dtype=np.uint8)

    def live_route(self) -> tuple:
        with self._state_lock:
            return (self.version, None)

    def live_version(self):
        with self._state_lock:
            return self.version

    def promote(self, version: str, cache) -> None:
        with self._state_lock:
            self.era += 1
            self.version = version
            self.era_of[version] = self.era
            cache.invalidate(reason=f"live -> {version}")


class _RouteBatcher:
    """Fake batcher under the CacheFront: submit() enqueues, a worker
    thread captures the live route (the engine-capture-at-dispatch
    model) and resolves the future — whose done-callback then runs the
    REAL single-flight completion inline, exactly like the production
    completion thread."""

    def __init__(self, route: _Route):
        self.route = route
        self._rid = itertools.count(1)
        self._q = make_fifo("harness.batcher.q")

    def next_rid(self) -> int:
        return next(self._rid)

    def submit(self, x, deadline_s=None, key=None, route=None,
               tags=None) -> Future:
        fut: Future = Future()
        fut.trace_id = None
        self._q.put((fut, int(x.shape[0])))
        return fut

    def worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, rows = item
            with self.route._state_lock:
                v, era = self.route.version, self.route.era
            fut.version = v
            fut.set_result(encode(v, era, rows))


class _DropFirstAppend(list):
    """The skip-follower mutation's followers list: silently drops the
    first registration."""

    def __init__(self):
        super().__init__()
        self._dropped = False

    def append(self, item) -> None:
        if not self._dropped:
            self._dropped = True
            return
        super().append(item)


def _broken_invalidate(self, reason=None) -> None:
    """The drop-epoch-bump mutation: entries cleared, epoch NOT bumped
    — an in-flight single-flight insert that raced the invalidation now
    lands instead of being refused."""
    with self._lock:
        self._entries.clear()
        self._invalidations += 1


class CacheMachine:
    """Single-flight collapse vs promote/rollback invalidation epoch:
    3 clients hammer two key classes while a promoter rolls
    v1 -> v2 -> v1 (each roll invalidating atomically with the route
    swap) and a worker thread resolves leaders."""

    name = "cache"

    def __init__(self, mutation: str = None):
        self.mutation = mutation
        self.futs: list = []
        self.route = None
        self.cache = None

    def run(self, ctl) -> None:
        from distributedmnist_tpu.serve import cache as cache_mod

        restore = None
        if self.mutation == "skip-follower":
            real = cache_mod._Flight

            def broken_flight(key, version, infer_dtype, epoch):
                fl = real(key, version, infer_dtype, epoch)
                fl.followers = _DropFirstAppend()
                return fl

            cache_mod._Flight = broken_flight
            restore = real
        try:
            self._run(ctl, cache_mod)
        finally:
            if restore is not None:
                cache_mod._Flight = restore

    def _run(self, ctl, cache_mod) -> None:
        self.route = route = _Route()
        self.cache = cache = cache_mod.PredictionCache(capacity=8)
        if self.mutation == "drop-epoch-bump":
            cache.invalidate = types.MethodType(_broken_invalidate,
                                                cache)
        batcher = _RouteBatcher(route)
        front = cache_mod.CacheFront(batcher, route, cache)
        hot = np.zeros((2, 4), np.uint8)      # shared hot key
        cold = np.ones((2, 4), np.uint8)

        def client(payload, k):
            def body():
                for _ in range(k):
                    self.futs.append(front.submit(payload))
            return body

        def promoter():
            route.promote("v2", cache)
            route.promote("v1", cache)

        threads = [
            ctl.spawn(client(hot, 2), "client-a"),
            ctl.spawn(client(hot, 2), "client-b"),
            ctl.spawn(client(cold, 1), "client-c"),
            ctl.spawn(promoter, "promoter"),
        ]
        worker = ctl.spawn(batcher.worker, "worker")
        for t in threads:
            t.join()
        for fut in list(self.futs):
            await_future(ctl, fut, "client-result")
        batcher._q.put(None)
        worker.join()

    def invariant(self, ctl) -> None:
        cache, route = self.cache, self.route
        if cache is None or route is None:
            return
        if not (ctl.lock_free("cache.state")
                and ctl.lock_free("harness.route.state")):
            return
        for key, entry in list(cache._entries.items()):
            assert (entry.version == key[0]
                    and entry.infer_dtype == key[1]), (
                f"cache entry/key identity mismatch: entry "
                f"({entry.version}, {entry.infer_dtype}) under key "
                f"({key[0]}, {key[1]})")
            v, era = decode(entry.logits)
            assert v == entry.version, (
                f"cache entry for {entry.version} holds bytes computed "
                f"by {v} — mixed-version bytes surfaced")
            current = route.era_of.get(v)
            assert era == current, (
                f"stale bytes cached: entry for {v} carries era {era} "
                f"but the route's current era for {v} is {current} — "
                "an insert crossed an invalidation (epoch bump "
                "dropped?)")

    def final(self, ctl) -> None:
        unresolved = [f for f in self.futs if not f.done()]
        assert not unresolved, (
            f"{len(unresolved)} submitted future(s) never resolved "
            "(skipped single-flight follower?)")
        assert not self.cache._flights, (
            "single-flight table not empty at drain: "
            f"{list(self.cache._flights)}")
        for fut in self.futs:
            v, era = decode(fut.result())
            assert v == fut.version, (
                f"result bytes from {v} tagged version {fut.version} — "
                "mixed-version response")
        self.invariant(ctl)


# -- machine 2: registry promote/rollback vs concurrent admin + eviction ---


class _RegEngine:
    """A warmed engine by fiat: compiles nothing, prices nothing —
    the registry's state machine is the subject, not XLA."""

    def __init__(self, version: str, infer_dtype: str = "float32"):
        self.version = version
        self.infer_dtype = infer_dtype
        self.max_batch = 8
        self.buckets = (8,)
        self.params = None

    def warmup(self, cost_samples: int = 5) -> int:
        return 0

    def bucket_costs(self) -> dict:
        return {}

    def bucket_costs_p95(self) -> dict:
        return {}

    def infer(self, x) -> np.ndarray:
        return np.zeros((np.asarray(x).shape[0], 10), np.float32)


class _RegFactory:
    max_batch = 8
    buckets = (8,)

    def make_engine(self, params, version, replica: int = 0,
                    infer_dtype: str = "float32") -> _RegEngine:
        return _RegEngine(version, infer_dtype)


class RegistryMachine:
    """Real ModelRegistry + real Router over fiat-warmed engines:
    concurrent add/promote/canary/rollback/describe with eviction
    pressure (max_versions=3). The contract: one live version, the
    router always points at a resident one, routed versions are never
    evicted."""

    name = "registry"

    def __init__(self):
        self.reg = None
        self.router = None

    def run(self, ctl) -> None:
        from distributedmnist_tpu.serve.registry import ModelRegistry
        from distributedmnist_tpu.serve.router import Router

        self.router = router = Router(max_batch=8, buckets=(8,),
                                      platform="cpu")
        self.reg = reg = ModelRegistry(_RegFactory(), router,
                                       max_versions=3)
        reg.add(None, version="v1")
        reg.promote("v1")
        expected = (KeyError, RuntimeError, ValueError)

        def admin_a():
            try:
                reg.add(None, version="v2")
                reg.promote("v2")
            except expected:
                pass

        def admin_b():
            try:
                reg.add(None, version="v3")
                reg.set_canary("v3", fraction=0.2)
            except expected:
                pass
            reg.clear_candidates()

        def evictor():
            try:
                reg.add(None, version="v4")
            except expected:
                pass

        def roller():
            reg.rollback("v2", reason="model-checker drill")

        def reader():
            for _ in range(3):
                reg.describe()
                reg.live_version()

        threads = [ctl.spawn(admin_a, "admin-a"),
                   ctl.spawn(admin_b, "admin-b"),
                   ctl.spawn(evictor, "evictor"),
                   ctl.spawn(roller, "roller"),
                   ctl.spawn(reader, "reader")]
        for t in threads:
            t.join()

    def invariant(self, ctl) -> None:
        reg, router = self.reg, self.router
        if reg is None or router is None:
            return
        if not (ctl.lock_free("registry.admin")
                and ctl.lock_free("registry.state")
                and ctl.lock_free("router.routes")):
            return
        # Quiescent reads go straight at the state (the controller
        # thread is not a controlled task; taking the shadow locks from
        # here would corrupt their ownership model).
        versions = dict(reg._versions)
        live = [name for name, mv in versions.items()
                if mv.state == "live"]
        assert len(live) <= 1, f"multiple live versions: {live}"
        live_t = router._live
        if live_t is not None:
            mv = versions.get(live_t.version)
            assert mv is not None, (
                f"router live target {live_t.version!r} was evicted "
                "from the registry")
            assert mv.state == "live", (
                f"router serves {live_t.version!r} but registry marks "
                f"it {mv.state!r}")
        in_route = {t.version for t in (router._live, router._canary,
                                        router._shadow)
                    if t is not None}
        missing = in_route - set(versions)
        assert not missing, (
            f"routed version(s) {sorted(missing)} evicted while still "
            "in the routing table")

    def final(self, ctl) -> None:
        assert self.router._live is not None, "no live version at drain"
        self.invariant(ctl)


# -- machine 3: batcher submit/shed/drain vs stop --------------------------


class _BatEngine:
    """Engine-shaped fake under the real DynamicBatcher: instant
    dispatch/fetch, no cost table (single-segment plans)."""

    max_batch = 8
    buckets = (4, 8)
    platform = "cpu"
    version = "v1"

    @staticmethod
    def _as_images(x) -> np.ndarray:
        return np.asarray(x, dtype=np.uint8)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds {self.buckets[-1]}")

    def bucket_costs(self) -> dict:
        return {}

    def dispatch(self, parts):
        rows = sum(np.asarray(p).shape[0] for p in parts)
        return types.SimpleNamespace(
            n=rows, bucket=self.bucket_for(rows), version=self.version,
            logits=np.full((rows, 10), 7.0, np.float32))

    def fetch(self, handle) -> np.ndarray:
        return handle.logits


class BatcherMachine:
    """Real DynamicBatcher (dispatch + completion threads under the
    controller) vs concurrent submits, deadline sheds, queue-watermark
    rejects and a racing stop(). The contract: every accepted future
    resolves, nothing is stranded by stop, and the in-flight window
    semaphore nets zero."""

    name = "batcher"

    def __init__(self, drain: bool = True):
        self.drain = drain
        self.batcher = None
        self.futs: list = []
        self.refused: list = []

    def run(self, ctl) -> None:
        import time

        from distributedmnist_tpu.serve.batcher import DynamicBatcher

        self.batcher = batcher = DynamicBatcher(
            _BatEngine(), max_batch=8, max_wait_us=1000, queue_depth=8,
            max_inflight=2, adaptive=False)
        batcher.start()

        def client(rows, use_deadline):
            def body():
                for _ in range(2):
                    try:
                        dl = (time.monotonic() + 0.002
                              if use_deadline else None)
                        self.futs.append(batcher.submit(
                            np.zeros((rows, 4), np.uint8),
                            deadline_s=dl))
                    except Exception as e:
                        # Rejected (watermark), DeadlineExceeded
                        # (expired at submit), RuntimeError (stopped)
                        self.refused.append(type(e).__name__)
            return body

        threads = [ctl.spawn(client(3, False), "client-a"),
                   ctl.spawn(client(2, True), "client-b"),
                   ctl.spawn(client(3, False), "client-c"),
                   ctl.spawn(lambda: batcher.stop(drain=self.drain),
                             "stopper")]
        for t in threads:
            t.join()
        batcher.stop(drain=True)       # idempotent second stop
        for fut in list(self.futs):
            await_future(ctl, fut, "client-result")

    def invariant(self, ctl) -> None:
        b = self.batcher
        if b is None:
            return
        if ctl.lock_free("batcher.inflight_gauge"):
            assert b._inflight >= 0, "in-flight count went negative"
            assert 0 <= b._dispatched <= b.max_inflight, (
                f"dispatched-but-unresolved {b._dispatched} outside "
                f"[0, {b.max_inflight}] — the window invariant")
        if ctl.lock_free("batcher.queue"):
            assert b._rows >= 0, "pending-row gauge went negative"

    def final(self, ctl) -> None:
        b = self.batcher
        unresolved = [f for f in self.futs if not f.done()]
        assert not unresolved, (
            f"{len(unresolved)} accepted future(s) never resolved "
            "across stop()")
        assert len(self.futs) + len(self.refused) == 6, (
            "client ops lost: "
            f"{len(self.futs)} futures + {len(self.refused)} refusals")
        assert b._rows == 0, f"pending rows {b._rows} at drain"
        assert b._inflight == 0 and b._dispatched == 0, (
            f"in-flight gauges nonzero at drain: {b._inflight}/"
            f"{b._dispatched}")
        balance = ctl.sem_balance.get("batcher.inflight_slots", 0)
        assert balance == 0, (
            f"window semaphore nets {balance:+d} at drain — a held "
            "slot no error path released")
        assert b._handles.empty(), "handle queue not drained"
        self.invariant(ctl)


# -- machine 4: fleet pick/failover/drain-rejoin ---------------------------


class _FleetRouter:
    """Per-replica router fake under the real ReplicaSet: version
    pointer under its own named lock, schedulable fetch faults, and a
    never-fetched-shaped handle so the abandoned-handle drain path
    (the PR 8 staging-leak fix) is explored too."""

    def __init__(self, rid: str):
        self.replica = rid
        self.max_batch = 8
        self.buckets = (8,)
        self.platform = "cpu"
        self.n_chips = 1
        self._lock = make_lock(f"harness.fleet.{rid}")
        self._live = "v1"
        self.fail_fetches = 0
        self.dispatched = 0

    def set_live(self, engine, version: str) -> None:
        with self._lock:
            self._live = version

    def set_shadow(self, engine, version, fraction) -> None:
        pass

    def set_canary(self, engine, version, fraction) -> None:
        pass

    def clear_candidates(self) -> None:
        pass

    def live_version(self):
        with self._lock:
            return self._live

    def live_infer_dtype(self):
        return None

    def live_route(self) -> tuple:
        with self._lock:
            return (self._live, None)

    def routes(self) -> dict:
        with self._lock:
            return {"live": self._live, "canary": None, "shadow": None}

    def versions_in_route(self) -> set:
        with self._lock:
            return {self._live}

    def bucket_costs(self) -> dict:
        return {8: 0.001}

    def bucket_costs_p95(self) -> dict:
        return {}

    def dispatch(self, parts):
        rows = sum(np.asarray(p).shape[0] for p in parts)
        with self._lock:
            self.dispatched += 1
            v = self._live
        return types.SimpleNamespace(
            version=v, n=rows, bucket=8, infer_dtype=None,
            handle=types.SimpleNamespace(staging="pinned"))

    def fetch(self, rh) -> np.ndarray:
        fail = False
        with self._lock:
            if self.fail_fetches > 0:
                self.fail_fetches -= 1
                fail = True
        if fail:
            raise RuntimeError(
                f"injected fetch fault on {self.replica}")
        return np.zeros((rh.n, 10), np.float32)


class FleetMachine:
    """Real ReplicaSet over 2 fake replica routers: 3 dispatch/fetch
    workers vs injected replica-fetch faults (failover), an admin
    drain/rejoin cycle, and a fleet-wide version roll. The contract:
    no mixed-version pick window, windows/outstanding cost net zero,
    and replica faults cost latency, never client errors."""

    name = "fleet"

    def __init__(self):
        self.fleet = None
        self.routers = None
        self.errors: list = []
        self.results: list = []

    def run(self, ctl) -> None:
        from distributedmnist_tpu.serve.fleet import ReplicaSet

        self.routers = [_FleetRouter("r0"), _FleetRouter("r1")]
        self.fleet = fleet = ReplicaSet(self.routers,
                                        per_replica_inflight=1)
        x = np.zeros((4, 28, 28, 1), np.uint8)

        def worker():
            for _ in range(2):
                try:
                    out = fleet.fetch(fleet.dispatch(x))
                    self.results.append(out.shape)
                except Exception as e:
                    self.errors.append(f"{type(e).__name__}: {e}")

        def failer():
            with self.routers[0]._lock:
                self.routers[0].fail_fetches = 2

        def admin():
            fleet.drain("r0")
            fleet.rejoin("r0")

        def roller():
            fleet.set_live([object(), object()], "v2")

        threads = [ctl.spawn(worker, "worker-a"),
                   ctl.spawn(worker, "worker-b"),
                   ctl.spawn(worker, "worker-c"),
                   ctl.spawn(failer, "failer"),
                   ctl.spawn(admin, "admin"),
                   ctl.spawn(roller, "roller")]
        for t in threads:
            t.join()

    def invariant(self, ctl) -> None:
        fleet = self.fleet
        if fleet is None:
            return
        if not ctl.lock_free("fleet.pick"):
            return
        live = {r._live for r in self.routers}
        assert len(live) == 1, (
            f"mixed-version pick window: replicas serve {sorted(live)}")
        for rep in fleet.replicas:
            assert rep.inflight >= 0, (
                f"replica {rep.rid} in-flight window went negative")
            assert rep.outstanding_s >= -1e-9, (
                f"replica {rep.rid} outstanding cost went negative")

    def final(self, ctl) -> None:
        assert not self.errors, (
            "replica faults leaked to clients instead of failing over: "
            f"{self.errors}")
        assert len(self.results) == 6, (
            f"lost client ops: {len(self.results)}/6 results")
        for rep in self.fleet.replicas:
            assert rep.inflight == 0, (
                f"replica {rep.rid} holds {rep.inflight} window "
                "slot(s) at drain")
            assert abs(rep.outstanding_s) < 1e-9, (
                f"replica {rep.rid} outstanding cost "
                f"{rep.outstanding_s} at drain")
        self.invariant(ctl)


class _FastEngine(_BatEngine):
    """_BatEngine plus a fast-lane route and double-dispatch
    accounting: every dispatched row is counted exactly once (fast or
    coalesced), so the machine can prove no request's rows ever
    dispatch twice across the two lanes."""

    def __init__(self):
        self._lock = make_lock("harness.fastengine")
        self.rows_dispatched = 0
        self.fast_dispatches = 0

    def dispatch(self, parts):
        h = super().dispatch(parts)
        with self._lock:
            self.rows_dispatched += h.n
        return h

    def dispatch_fast(self, x):
        x = np.asarray(x)
        with self._lock:
            self.rows_dispatched += x.shape[0]
            self.fast_dispatches += 1
        return types.SimpleNamespace(
            n=x.shape[0], bucket=self.bucket_for(x.shape[0]),
            version=self.version,
            logits=np.full((x.shape[0], 10), 7.0, np.float32))


class FastlaneBatcherMachine(BatcherMachine):
    """The bypass lane's concurrency contract (ISSUE 14): the real
    DynamicBatcher with fastlane=True at max_inflight=1 — the
    tightest window, where the lane and the dispatch thread compete
    for ONE slot — under racing submits and a racing stop(). Proven
    on every explored schedule: no deadlock (the explorer's own
    detector), every accepted future resolves exactly once, no
    request's rows dispatch twice across the two lanes, and the
    window semaphore nets zero (a lane that leaked its try-acquired
    slot would strand the dispatch thread forever)."""

    name = "batcher-fastlane"

    def run(self, ctl) -> None:
        import time

        from distributedmnist_tpu.serve.batcher import DynamicBatcher

        self.engine = _FastEngine()
        self.batcher = batcher = DynamicBatcher(
            self.engine, max_batch=8, max_wait_us=1000, queue_depth=8,
            max_inflight=1, adaptive=False, fastlane=True)
        batcher.start()

        def client(rows, use_deadline):
            def body():
                for _ in range(2):
                    try:
                        dl = (time.monotonic() + 0.002
                              if use_deadline else None)
                        self.futs.append(batcher.submit(
                            np.zeros((rows, 4), np.uint8),
                            deadline_s=dl))
                    except Exception as e:
                        self.refused.append(type(e).__name__)
            return body

        threads = [ctl.spawn(client(3, False), "client-a"),
                   ctl.spawn(client(1, False), "client-b"),
                   ctl.spawn(client(2, True), "client-c"),
                   ctl.spawn(lambda: batcher.stop(drain=self.drain),
                             "stopper")]
        for t in threads:
            t.join()
        batcher.stop(drain=True)
        for fut in list(self.futs):
            await_future(ctl, fut, "client-result")

    def final(self, ctl) -> None:
        super().final(ctl)
        # No double dispatch: rows the engine saw == rows of futures
        # that resolved successfully (refusals and sheds never reach
        # the engine; a row dispatched by BOTH lanes would overshoot).
        served = sum(f.result().shape[0] for f in self.futs
                     if f.exception() is None)
        assert self.engine.rows_dispatched == served, (
            f"engine dispatched {self.engine.rows_dispatched} rows but "
            f"{served} rows resolved — a request dispatched twice "
            "(or was lost) across the lanes")


# -- machine 6: global scheduler WFQ/EDF fairness (ISSUE 18) ---------------


class _TenRouter:
    """Router-shaped fake under the real GlobalScheduler: always
    resident, empty cost table (dispatch pricing falls back to the
    default per-row estimate — deterministic, schedule-independent)."""

    @staticmethod
    def live_version():
        return "v1"

    @staticmethod
    def live_infer_dtype():
        return "float32"

    @staticmethod
    def bucket_costs():
        return {}

    @staticmethod
    def _as_images(x) -> np.ndarray:
        return np.asarray(x, dtype=np.uint8)


class _TenBatcher:
    """Inline-resolving per-model queue fake: submit() returns an
    already-resolved future (zero service time). The machine explores
    the SCHEDULER's interleavings — admission vs grant loop vs admin
    vs stop; the batcher's own races are BatcherMachine's job."""

    def __init__(self, name: str):
        self.name = name
        self.rows_forwarded = 0

    def submit(self, x, deadline_s=None, route=None, tags=None):
        arr = np.asarray(x)
        self.rows_forwarded += arr.shape[0]     # forward thread only
        fut: Future = Future()
        fut.set_result(np.zeros((arr.shape[0], 10), np.float32))
        return fut

    @staticmethod
    def pending_rows() -> int:
        return 0

    def stop(self, drain: bool = True) -> None:
        pass


class SchedulerWFQMachine:
    """Real GlobalScheduler (grant loop under the controller) over a
    two-model fake catalog: a light weight-2 tenant and a heavy
    bursty tenant racing concurrent submits, a live set_quota admin
    call, and a draining stop(). The contract: every accepted future
    resolves across stop, no client op is lost, per-tenant pending-row
    accounting always matches the queues' actual contents, and the
    DRR consecutive-skip starvation bound holds (bounded head-of-line
    blocking — also asserted inside every grant)."""

    name = "scheduler-wfq"
    OPS = 7          # 2 light + 3 heavy + 2 deadlined light

    def __init__(self):
        self.sched = None
        self.futs: list = []
        self.refused: list = []

    def run(self, ctl) -> None:
        import time

        from distributedmnist_tpu.serve.tenancy import (CatalogEntry,
                                                        GlobalScheduler,
                                                        ModelCatalog,
                                                        SLOClass)

        catalog = ModelCatalog()
        for m in ("mlp", "lenet"):
            catalog.add(CatalogEntry(
                name=m, registry=None, router=_TenRouter(),
                factory=types.SimpleNamespace(max_batch=8,
                                              buckets=(4, 8),
                                              platform="cpu"),
                batcher=_TenBatcher(m)))
        tenants = {
            "light": SLOClass(name="light", weight=2.0),
            "heavy": SLOClass(name="heavy", weight=1.0,
                              model="lenet"),
        }
        self.sched = sched = GlobalScheduler(
            catalog, tenants, quantum_s=0.001, tenant_queue_rows=64)
        sched.start()

        def client(tenant, rows, n_ops, use_deadline=False):
            def body():
                for _ in range(n_ops):
                    try:
                        dl = (time.monotonic() + 0.002
                              if use_deadline else None)
                        self.futs.append(sched.submit(
                            np.zeros((rows, 4), np.uint8),
                            tenant=tenant, deadline_s=dl))
                    except Exception as e:
                        # QuotaExceeded / Rejected (watermark) /
                        # DeadlineExceeded (expired at the door) /
                        # RuntimeError (stopped)
                        self.refused.append(type(e).__name__)
            return body

        threads = [
            ctl.spawn(client("light", 2, 2), "light"),
            ctl.spawn(client("heavy", 6, 3), "heavy-burst"),
            ctl.spawn(client("light", 1, 2, use_deadline=True),
                      "light-deadlined"),
            ctl.spawn(lambda: sched.set_quota("light", qps=1000.0,
                                              burst=64.0), "admin"),
        ]
        for t in threads:
            t.join()
        sched.stop(drain=True)
        for fut in list(self.futs):
            await_future(ctl, fut, "tenant-result")

    def invariant(self, ctl) -> None:
        s = self.sched
        if s is None:
            return
        if ctl.lock_free("tenancy.sched"):
            qrows: dict = {}
            for (t, _m), q in s._queues.items():
                qrows[t] = qrows.get(t, 0) + sum(r.n for r in q)
            for t, rows in s._pending_rows.items():
                assert rows >= 0, (
                    f"tenant {t} pending rows went negative: {rows}")
                assert qrows.get(t, 0) == rows, (
                    f"tenant {t} pending-row gauge {rows} disagrees "
                    f"with queue contents {qrows.get(t, 0)} — torn "
                    "admission/grant accounting")
            self._check_skip_bound(s)

    @staticmethod
    def _check_skip_bound(s) -> None:
        from distributedmnist_tpu.serve import scheduler as policy

        if s._max_head_cost_s <= 0:
            return
        weights = [c.weight for c in s._classes.values()]
        bound = policy.drr_skip_bound(len(s._ring),
                                      s._max_head_cost_s,
                                      s.quantum_s, min(weights))
        assert s.max_skip_observed <= bound, (
            f"WFQ starvation: a tenant was passed over "
            f"{s.max_skip_observed} consecutive grants "
            f"(bound {bound})")

    def final(self, ctl) -> None:
        s = self.sched
        unresolved = [f for f in self.futs if not f.done()]
        assert not unresolved, (
            f"{len(unresolved)} admitted future(s) never resolved "
            "across stop(drain=True)")
        assert len(self.futs) + len(self.refused) == self.OPS, (
            "client ops lost: "
            f"{len(self.futs)} futures + {len(self.refused)} refusals "
            f"!= {self.OPS}")
        assert all(rows == 0 for rows in s._pending_rows.values()), (
            f"pending rows at drain: {s._pending_rows}")
        assert all(not q for q in s._queues.values()), (
            "non-empty tenant queue at drain")
        self._check_skip_bound(s)
        self.invariant(ctl)


# -- machine 7: autoscaler control loop (ISSUE 20) -------------------------


class _ScaleRecorder:
    """Actuator-shaped fake under the real Autoscaler: records every
    scale_to target, applies the clamp a real actuator would, and can
    die exactly once mid-decision (`die_on_call` = index of the call
    that raises BEFORE applying — the worker-died-while-draining case).
    The entry/exit counters straddle the instrumented state lock, so if
    two actuations ever overlap there is a schedule where both callers
    sit inside scale_to at once and `overlaps` catches it."""

    kind = "fake"
    cost_basis = "fake-units"

    def __init__(self, floor: int, ceiling: int,
                 die_on_call: int = None):
        self.floor = floor
        self.ceiling = ceiling
        self._lock = make_lock("harness.asc.units")
        self._units = floor
        self.calls: list = []
        self.deaths = 0
        self._die_on_call = die_on_call
        self._in_flight = 0          # mutated only between yield points
        self.overlaps = 0

    def current(self) -> int:
        with self._lock:
            return self._units

    def scale_to(self, units: int) -> int:
        self._in_flight += 1
        if self._in_flight > 1:
            self.overlaps += 1
        try:
            with self._lock:
                n = len(self.calls)
                self.calls.append(units)
                if self._die_on_call is not None and n == self._die_on_call:
                    self.deaths += 1
                    raise RuntimeError(
                        "worker died mid-drain (injected)")
                self._units = min(max(units, self.floor), self.ceiling)
                return self._units
        finally:
            self._in_flight -= 1

    def capacity_rows_per_s(self, units: int):
        return 100.0 * min(max(units, 1), self.ceiling)

    def chip_fraction(self, units: int) -> float:
        return float(min(max(units, 1), self.ceiling))

    def close(self) -> None:
        pass


class _SignalBox:
    """Mutable saturation surface: the load-spike thread writes a
    pressure level, the control loop reads it through the same
    instrumented lock — every read is a yield point, so decisions can
    land on either side of a spike edge."""

    def __init__(self):
        self._lock = make_lock("harness.asc.signals")
        self._queue_frac = 0.0

    def set(self, frac: float) -> None:
        with self._lock:
            self._queue_frac = frac

    def read(self):
        from distributedmnist_tpu.serve.autoscale import Signals

        with self._lock:
            return Signals(queue_frac=self._queue_frac,
                           inflight_frac=0.0, shed_delta=0)


class AutoscalerLoopMachine:
    """The REAL Autoscaler control loop (ISSUE 20) over a recording
    fake actuator and a mutable signal box: the started loop thread
    races a load-spike driver (manual tick()s at pressure 1.0, then a
    drop to trough), a second trough driver, one injected mid-decision
    actuator death, and a racing stop(). The contract: no deadlock
    (the explorer's own detector), actuations NEVER overlap (the admin
    lock serializes manual ticks against the loop), every target the
    loop hands the actuator and every achieved scale stays inside
    [floor, ceiling], the injected death is absorbed as a counted
    error with the loop still alive to act again, and stop() joins the
    loop thread even when it lands mid-decision."""

    name = "autoscaler-loop"

    def __init__(self):
        self.act = None
        self.asc = None

    def run(self, ctl) -> None:
        import logging

        from distributedmnist_tpu.serve.autoscale import Autoscaler

        # the injected death is EXPECTED here — don't spray its
        # warning across every explored schedule's output
        logging.getLogger("serve.autoscale").setLevel(logging.ERROR)
        self.act = _ScaleRecorder(floor=1, ceiling=3, die_on_call=1)
        self.sigs = _SignalBox()
        # cooldown 0: every decision may act, so the overlap/bounds
        # invariants face the max actuation rate (flap counting is the
        # bench's job; this machine stresses the serialization)
        self.asc = asc = Autoscaler(
            self.act, self.sigs.read, high=0.7, low=0.2,
            cooldown_s=0.0, interval_s=0.001)
        asc.start()

        def spike():
            # pin pressure above the high band, force decisions racing
            # the loop thread's own ticks, then drop off the cliff
            self.sigs.set(1.0)
            for _ in range(3):
                asc.tick()
            self.sigs.set(0.0)
            asc.tick()

        def trough():
            self.sigs.set(0.05)
            asc.tick()

        threads = [ctl.spawn(spike, "load-spike"),
                   ctl.spawn(trough, "trough"),
                   ctl.spawn(asc.stop, "stopper")]
        for t in threads:
            t.join()
        asc.stop()              # idempotent: second stop is a no-op

    def invariant(self, ctl) -> None:
        a = self.act
        if a is None:
            return
        assert a.overlaps == 0, (
            f"{a.overlaps} overlapping actuation(s) — the admin lock "
            "failed to serialize a manual tick against the loop")
        if ctl.lock_free("harness.asc.units"):
            assert a.floor <= a._units <= a.ceiling, (
                f"scale {a._units} escaped [{a.floor}, {a.ceiling}]")

    def final(self, ctl) -> None:
        a, asc = self.act, self.asc
        assert asc._thread is None, "loop thread not joined by stop()"
        assert a.overlaps == 0, (
            f"{a.overlaps} overlapping actuation(s) at drain")
        assert all(a.floor <= u <= a.ceiling for u in a.calls), (
            f"loop handed the actuator an out-of-bounds target: "
            f"{a.calls} outside [{a.floor}, {a.ceiling}]")
        assert a.floor <= a.current() <= a.ceiling, (
            f"final scale {a.current()} outside "
            f"[{a.floor}, {a.ceiling}]")
        for rec in asc.actions:
            assert a.floor <= rec["achieved_units"] <= a.ceiling, (
                f"action log records out-of-bounds scale: {rec}")
        assert asc.errors == a.deaths, (
            f"{a.deaths} injected death(s) but {asc.errors} counted "
            "error(s) — a failure was double-counted or swallowed")
        assert asc.flaps() == 0
        self.invariant(ctl)


def _batcher_nodrain() -> BatcherMachine:
    return BatcherMachine(drain=False)


MACHINES = {
    "cache": CacheMachine,
    "registry": RegistryMachine,
    "batcher": BatcherMachine,
    # stop(drain=False) is the path whose resolve-under-lock race this
    # PR fixed (lint DML009): it gets its own explored machine so the
    # fix is pinned dynamically too, not just statically.
    "batcher-nodrain": _batcher_nodrain,
    # bypass-vs-coalesce racing submits at max_inflight=1 (ISSUE 14):
    # never deadlock, never double-dispatch, never strand the window
    # semaphore.
    "batcher-fastlane": FastlaneBatcherMachine,
    "fleet": FleetMachine,
    # the global scheduler's WFQ/EDF fairness vs racing admission,
    # quota admin and stop (ISSUE 18): accepted futures all resolve,
    # queue accounting never tears, head-of-line blocking stays under
    # the asserted DRR skip bound.
    "scheduler-wfq": SchedulerWFQMachine,
    # the autoscaler's closed loop vs load spikes, a mid-decision
    # actuator death and racing stop() (ISSUE 20): actuations never
    # overlap, scale never escapes [floor, ceiling], the death is a
    # counted error and the loop joins cleanly.
    "autoscaler-loop": AutoscalerLoopMachine,
}
