"""Named threading-primitive factory for the serving stack (ISSUE 8).

Every lock, condition, semaphore and thread in serve/ is constructed
through these factories instead of bare `threading.*` calls (the
project lint's DML001/DML002 enforce it). The names are the nodes of
the sanitizer's lock-order graph — "registry.state -> router.routes"
is a meaningful invariant, "lock at 0x7f.. -> lock at 0x7f.." is not —
and `blocking_ok=True` marks the deliberately-slow locks (the registry
admin RLock serializes multi-second warmups BY DESIGN) that the
blocking-under-lock check must not flag.

With no sanitizer installed (every production process) each factory
returns the bare threading primitive: no wrapper object exists, the
hot path is bit-identical to pre-ISSUE-8 code. With one installed
(tests' conftest fixture, or DMNIST_SANITIZE=1) the factories return
thin instrumented wrappers whose acquire/release feed the sanitizer's
per-thread held stack; the wrappers stay valid across uninstall (each
hook re-checks the active sanitizer), so objects built under one test's
sanitizer keep working inert in the next.
"""

from __future__ import annotations

import queue
import threading

from distributedmnist_tpu.analysis import sanitize


def _controller():
    """The active schedule-exploration controller, or None. Imported
    lazily so `python -m distributedmnist_tpu.analysis.explore` does
    not re-execute an already-imported module (runpy warning); the
    cost is one sys.modules lookup per factory CALL — construction
    time only, never the serving hot path."""
    from distributedmnist_tpu.analysis import explore

    return explore.active_controller()


class _SanLock:
    """Instrumented non-reentrant lock: threading.Lock plus sanitizer
    bookkeeping on successful acquire / release."""

    def __init__(self, name: str, blocking_ok: bool = False):
        self._name = name
        self._blocking_ok = blocking_ok
        self._inner = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            san = sanitize.active_sanitizer()
            if san is not None:
                san.on_acquired(self._name, id(self), self._blocking_ok)
        return ok

    def release(self) -> None:
        san = sanitize.active_sanitizer()
        if san is not None:
            san.on_released(self._name, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanLock {self._name!r} {self._inner!r}>"


class _SanRLock:
    """Instrumented re-entrant lock. Only the OUTERMOST acquire/release
    of a thread's hold touches the sanitizer (re-entry is not a new
    edge — it is the same hold); depth is tracked per-thread."""

    def __init__(self, name: str, blocking_ok: bool = False):
        self._name = name
        self._blocking_ok = blocking_ok
        self._inner = threading.RLock()
        self._tls = threading.local()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            depth = getattr(self._tls, "depth", 0)
            self._tls.depth = depth + 1
            if depth == 0:
                san = sanitize.active_sanitizer()
                if san is not None:
                    san.on_acquired(self._name, id(self),
                                    self._blocking_ok)
        return ok

    def release(self) -> None:
        depth = getattr(self._tls, "depth", 0) - 1
        self._tls.depth = depth
        if depth == 0:
            san = sanitize.active_sanitizer()
            if san is not None:
                san.on_released(self._name, id(self))
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # The threading.Condition lock protocol, delegated to the inner
    # RLock: Condition.wait() releases ALL recursion levels and
    # restores them on wake — the held-stack bookkeeping must mirror
    # that, or the sanitizer would think a waiting thread still holds
    # the lock. Production Condition() is RLock-backed, so sanitized
    # conditions must be too: a reentrant condition-lock path that
    # works in production must not silently deadlock under the
    # sanitizer (the one failure shape this package must never cause).
    def _release_save(self):
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = 0
        if depth > 0:
            san = sanitize.active_sanitizer()
            if san is not None:
                san.on_released(self._name, id(self))
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        self._tls.depth = depth
        if depth > 0:
            san = sanitize.active_sanitizer()
            if san is not None:
                san.on_acquired(self._name, id(self), self._blocking_ok)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __repr__(self) -> str:
        return f"<SanRLock {self._name!r} {self._inner!r}>"


class _SanSemaphore:
    """Instrumented semaphore doubling as a resource-balance counter:
    every acquire checks one unit of `name` out, every release returns
    it — at drain the sanitizer's balance for `name` must read zero
    (the in-flight window slot contract the batcher relies on)."""

    def __init__(self, name: str, value: int = 1):
        self._name = name
        self._inner = threading.Semaphore(value)

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True,
                timeout: float | None = None) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            sanitize.resource_acquire(self._name)
        return ok

    def release(self, n: int = 1) -> None:
        san = sanitize.active_sanitizer()
        if san is not None:
            san.on_resource(self._name, -n)
        self._inner.release(n)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanSemaphore {self._name!r} {self._inner!r}>"


def make_lock(name: str, blocking_ok: bool = False):
    """A named mutex: bare threading.Lock when no sanitizer is active,
    an instrumented wrapper when one is, and a schedule-explorer shadow
    lock while a model-checking Controller is installed (ISSUE 11 —
    every acquire/release becomes a controller yield point).
    `blocking_ok=True` exempts holders from the blocking-under-lock
    check (use for locks that serialize slow work BY DESIGN, e.g. admin
    locks held across warmups — never for anything the dispatch/
    completion path crosses)."""
    ctl = _controller()
    if ctl is not None:
        return ctl.new_lock(name)
    if sanitize.active_sanitizer() is None:
        return threading.Lock()
    return _SanLock(name, blocking_ok=blocking_ok)


def make_rlock(name: str, blocking_ok: bool = False):
    ctl = _controller()
    if ctl is not None:
        return ctl.new_rlock(name)
    if sanitize.active_sanitizer() is None:
        return threading.RLock()
    return _SanRLock(name, blocking_ok=blocking_ok)


def make_condition(name: str, blocking_ok: bool = False):
    """A named condition variable. The sanitized variant wraps a
    _SanRLock — the same reentrant semantics as a production
    `threading.Condition()` (whose default lock is an RLock), so a
    reentrant condition-lock path behaves identically sanitized and
    not. wait() releases through the wrapper's Condition protocol
    (_release_save/_acquire_restore), so the held-stack stays truthful
    across waits at any recursion depth. Under an explorer Controller
    the condition is a shadow state machine whose untimed wait() wakes
    only on notify — lost wakeups become reachable deadlocks."""
    ctl = _controller()
    if ctl is not None:
        return ctl.new_condition(name)
    if sanitize.active_sanitizer() is None:
        return threading.Condition()
    return threading.Condition(_SanRLock(name, blocking_ok=blocking_ok))


def make_semaphore(name: str, value: int = 1):
    """A named counting semaphore whose holds are resource-balanced by
    the sanitizer (net zero at drain, never negative) and schedule-
    explored under a Controller."""
    ctl = _controller()
    if ctl is not None:
        return ctl.new_semaphore(name, value)
    if sanitize.active_sanitizer() is None:
        return threading.Semaphore(value)
    return _SanSemaphore(name, value)


def make_fifo(name: str):
    """A named unbounded FIFO hand-off queue (the serve idiom for
    dispatch->completion handle queues and the shadow-comparison
    queue). Production and sanitized runs get a bare queue.SimpleQueue
    — there is nothing to balance-check, put never blocks. Under an
    explorer Controller the FIFO is a shadow queue whose get() is a
    yield point parked on non-empty, so the batcher's completion
    hand-off is explorable instead of an uninstrumented real block
    (ISSUE 11)."""
    ctl = _controller()
    if ctl is not None:
        return ctl.new_fifo(name)
    return queue.SimpleQueue()


def make_thread(target, name: str, daemon: bool, args: tuple = (),
                kwargs: dict | None = None) -> threading.Thread:
    """The registered thread constructor for serve/: `daemon` is a
    REQUIRED argument — the PR 2-6 review rounds repeatedly caught
    threads that forgot daemon=True and stranded pytest at exit, so
    the choice must be written down at every spawn site. Under a
    sanitizer the thread is registered for the leaked-non-daemon-thread
    report; under an explorer Controller the thread is a controlled
    (scheduler-gated) thread whose join is cooperative."""
    ctl = _controller()
    if ctl is not None:
        return ctl.new_thread(target, name, daemon, args, kwargs)
    t = threading.Thread(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=daemon)
    san = sanitize.active_sanitizer()
    if san is not None:
        san.register_thread(t)
    return t
