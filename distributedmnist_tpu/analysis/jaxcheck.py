"""Static compile-surface auditor (ISSUE 12): prove the serving fleet's
JAX cache-key universe closed, transfer-clean and fully warmed — before
anything runs.

Every hot-path guarantee since PR 1 rests on a RUNTIME compile counter
noticing a recompile after the fact. This module is the ahead-of-time
sibling (the concurrency plane got its own in PRs 8/11 — lint + model
checker): it abstract-evaluates every forward the serving stack could
ever dispatch — `jax.eval_shape` / `jax.make_jaxpr` over
ShapeDtypeStructs, no device work, no data — and checks four properties
statically:

1. **Closed cache-key universe** (JX001/JX002). A jitted forward's
   cache key is (function instance, input avals); one engine serves one
   jitted forward whose per-bucket specializations are jit's own shape
   cache, so the static key universe of a deployment is
   {(model, infer_dtype, fused_mode, bucket rung)}. The REACHABLE side
   is derived from request-admission semantics (every bucket
   `bucket_for` can return for an admissible size 1..max_batch, which
   also covers the registry's parity-gate batch); the WARMED side is
   derived by running the real `InferenceEngine.warmup` against a
   shape-recording probe (so a warmup edit that skips a rung is caught,
   not assumed away), with the variant set mirroring what
   `ModelRegistry.activate_infer_dtype` would warm. A
   reachable-but-unwarmed key is a steady-state recompile waiting to
   happen (the Clockwork violation); a warmed-but-unreachable key is
   dead warmup cost.
2. **Transfer hygiene** (JX003). The abstract pass runs under
   `jax.transfer_guard("disallow")`, and each traced jaxpr's consts are
   scanned for captured host ndarrays: a forward that closes over a
   host array re-stages it implicitly instead of through the engine's
   pooled staging + device_put path (lint DML012 polices the same class
   at the AST level in serve/).
3. **Weak-type / dtype drift** (JX004). A Python scalar reaching a
   jitted forward as an ARGUMENT traces weak-typed and silently splits
   the cache key against the committed-array spelling of the same call
   (lint DML013's runtime shape); float64 avals or consts under the
   repo's disabled-x64 regime are precision drift. Both are scanned in
   the abstract values, where they are visible before any dispatch.
4. **Graph fingerprints** (JX005). Each served forward's canonicalized
   jaxpr is hashed into a stable fingerprint, snapshotted in-repo
   (analysis/jaxpr_fingerprints.json). A PR that silently changes a
   compiled serving graph — numerics, layer routing, quantization
   scheme — fails the gate until the snapshot is regenerated with
   `--update-snapshots --reason "..."`: the same
   codify-past-review-findings stance as DML001-011, covering the PR 3
   trap (thread-local default_device in the cache key) as a CLASS. The
   training-step graphs train.py compiles are fingerprinted too.

CLI: `python -m distributedmnist_tpu.analysis.jaxcheck` — exit 0 on a
closed, clean, snapshot-matching surface; 1 on findings; 2 on internal
error. `--emit` (or DMNIST_JAXCHECK_ARTIFACT=1) writes an
ANALYSIS_r*.json round record via the PR 11 report machinery.
scripts/tier1.sh runs the default audit after lint and the explorer
smoke; scripts/jaxcheck.sh is the long-form artifact-emitting runner.

Everything traces on the CPU host with a fixed 1-device canonical
geometry, so the snapshot is identical under tier-1's bare CLI and the
test suite's 8-virtual-device conftest.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
from typing import Any, Callable, Optional, Sequence

import numpy as np

# Rule registry: ID -> (summary, the hazard class it encodes).
RULES = {
    "JX001": (
        "reachable-but-unwarmed jit cache key",
        "a bucket rung a live request could land in that warmup never "
        "compiled: the first request to hit it pays a steady-state "
        "XLA compile — exactly the tail-latency poison Clockwork's "
        "never-compile-on-the-hot-path rule (and every "
        "recompiles_after_warmup == 0 assertion since PR 1) exists to "
        "prevent"),
    "JX002": (
        "warmed-but-unreachable jit cache key",
        "a bucket rung warmup compiles that no admissible request size "
        "can ever reach: dead warmup cost on every version load and "
        "swap, silently taxing promote latency and HBM"),
    "JX003": (
        "implicit host->device transfer in a served forward",
        "the forward captures a host ndarray (a jaxpr const) instead "
        "of taking it as a staged argument: the bytes bypass the "
        "engine's pooled staging + device_put discipline and re-stage "
        "on every program instantiation — the np-array-into-jitted-"
        "call leak, caught abstractly under jax.transfer_guard "
        "semantics (lint DML012 is the AST-level sibling in serve/)"),
    "JX004": (
        "weak-type / dtype drift splitting the jit cache key",
        "a weak-typed (Python scalar) argument traces a DIFFERENT "
        "cache key than the committed-array spelling of the same call "
        "— one logical program, two compiles the counter cannot "
        "attribute; float64 under the repo's disabled-x64 regime is "
        "silent precision drift (lint DML013 is the AST-level "
        "sibling)"),
    "JX005": (
        "jaxpr fingerprint drift vs the committed snapshot",
        "a compiled serving graph changed without the snapshot being "
        "regenerated: either an intended forward edit missing its "
        "`--update-snapshots --reason` paper trail, or an UNintended "
        "graph change riding along in a refactor — both must fail "
        "until stated"),
}

SNAPSHOT_BASENAME = "jaxpr_fingerprints.json"

# The canonical audited row geometry (the serving contract's image
# shape; engine.py's IMAGE_SHAPE without importing jax at module load).
_IMAGE_SHAPE = (28, 28, 1)


def snapshot_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        SNAPSHOT_BASENAME)


@dataclasses.dataclass
class Finding:
    rule: str
    key: str          # the compile key / snapshot key the finding names
    message: str

    def format(self) -> str:
        return f"{self.rule} [{self.key}] {self.message}"


def key_str(model: str, infer_dtype: str, fused_mode: str,
            bucket: int) -> str:
    return f"{model}/{infer_dtype}/{fused_mode}/b{bucket}"


# -- the audited deployment shape ------------------------------------------


@dataclasses.dataclass(frozen=True)
class AuditTarget:
    """One deployment shape to audit: the knobs that decide which
    compiled programs the registry could ever serve. Mirrors the
    serving fields of Config (from_config lifts one); the canonical
    defaults pin a 1-device geometry so fingerprints are identical on
    every host the gate runs on."""

    model: str
    serve_max_batch: int = 512
    n_chips: int = 1
    serve_infer_dtype: str = "auto"
    fused_kernels: str = "auto"
    dtype: str = "float32"                 # cfg.dtype (compute dtype)
    buckets: Optional[tuple] = None        # explicit ladder override

    @classmethod
    def from_config(cls, cfg, n_chips: int = 1,
                    buckets: Optional[Sequence[int]] = None
                    ) -> "AuditTarget":
        return cls(model=cfg.model, serve_max_batch=cfg.serve_max_batch,
                   n_chips=n_chips,
                   serve_infer_dtype=cfg.serve_infer_dtype,
                   fused_kernels=cfg.fused_kernels, dtype=cfg.dtype,
                   buckets=tuple(buckets) if buckets else None)

    def label(self) -> str:
        return (f"{self.model}-mb{self.serve_max_batch}"
                f"-c{self.n_chips}-{self.serve_infer_dtype}"
                f"-{self.fused_kernels}")


def default_targets() -> list:
    """The gate's audit set: both models, the full auto dtype-variant
    universe, on BOTH fused-kernel routes a deployment can pin (auto ->
    XLA on the CPU audit host; pallas -> the Pallas kernels in
    interpret mode — same graphs the TPU route compiles, minus the
    backend lowering)."""
    return [AuditTarget(model=m, fused_kernels=f)
            for m in ("mlp", "lenet") for f in ("auto", "pallas")]


def dtype_universe(serve_infer_dtype: str) -> tuple:
    """Every serving precision the registry could route for this
    setting: the f32 base always (bootstrap promotes it, and a refused
    variant demotes back to it), plus the gated variant set —
    registry.PARITY_GATES is read live so a new variant dtype widens
    the audited universe automatically."""
    from distributedmnist_tpu.serve.registry import PARITY_GATES

    if serve_infer_dtype == "auto":
        return ("float32",) + tuple(sorted(PARITY_GATES))
    if serve_infer_dtype == "float32":
        return ("float32",)
    if serve_infer_dtype not in PARITY_GATES:
        raise ValueError(
            f"unknown serve_infer_dtype {serve_infer_dtype!r} (known: "
            f"float32, auto, {sorted(PARITY_GATES)})")
    return ("float32", serve_infer_dtype)


# -- key universe: reachable vs warmed -------------------------------------


def reachable_buckets(buckets: Sequence[int], max_batch: int) -> set:
    """Bucket rungs an admissible request could land in: the image of
    bucket_for over sizes 1..max_batch (pad-and-slice admission — the
    batcher caps coalesced drains at max_batch, bisection only ever
    shrinks, and the registry's parity batch is capped at max_batch
    too, so this image IS the dispatchable set). The fast lane's
    row-staged program (ISSUE 14) is one more reachable key when the
    geometry has one — represented as the string '<rung>-row' beside
    the int rungs, and derived from the engine's OWN rule
    (engine.fast_row_bucket), so the reachable side can never drift
    from what dispatch_fast actually routes."""
    from distributedmnist_tpu.serve.engine import fast_row_bucket

    ladder = sorted(set(buckets))
    out: set = set()
    for n in range(1, max_batch + 1):
        for b in ladder:
            if b >= n:
                out.add(b)
                break
    rb = fast_row_bucket(buckets)
    if rb is not None:
        out.add(f"{rb}-row")
    return out


class _WarmupProbe:
    """A shape-recording engine double the REAL InferenceEngine.warmup
    runs against: records which bucket each warmup infer() would land
    in (via the engine's own bucket_for) instead of computing. Keeps
    the warmed set derived from the warmup CODE, not from a model of
    it — a warmup edit that skips a rung changes the probe's record."""

    def __init__(self, buckets: Sequence[int], infer_dtype: str):
        self.buckets = tuple(sorted(set(buckets)))
        self.infer_dtype = infer_dtype
        self.warmed: set = set()
        self._bucket_cost: dict = {}
        self._bucket_cost_p95: dict = {}

        class _NullCounter:
            def snapshot(self) -> int:
                return 0

        self._compiles = _NullCounter()

    def bucket_for(self, n: int) -> int:
        from distributedmnist_tpu.serve.engine import InferenceEngine

        return InferenceEngine.bucket_for(self, n)

    def infer(self, x) -> None:
        self.warmed.add(self.bucket_for(x.shape[0]))

    def _warm_fastlane(self, costs=None) -> None:
        """The probe's record of the real warmup's fast-lane pass
        (ISSUE 14): warmup calls this unconditionally; which rung (if
        any) gets a row-staged program comes from the engine's own
        fast_row_bucket rule, same as the reachable side. (The cost
        gate only decides whether the route SERVES; the key is
        compiled either way, which is what the closure audits.)"""
        from distributedmnist_tpu.serve.engine import fast_row_bucket

        rb = fast_row_bucket(self.buckets)
        if rb is not None:
            self.warmed.add(f"{rb}-row")


def warmed_buckets(buckets: Sequence[int], infer_dtype: str) -> set:
    """The rungs `InferenceEngine.warmup` actually compiles for one
    engine of this geometry, derived by running the real warmup against
    a recording probe (module-level so tests can plant a regression)."""
    from distributedmnist_tpu.serve.engine import InferenceEngine

    probe = _WarmupProbe(buckets, infer_dtype)
    InferenceEngine.warmup(probe, cost_samples=1)
    return probe.warmed


def crosscheck_keys(model: str, fused_mode: str, static: dict,
                    warmed: dict, max_batch: int) -> list:
    """JX001/JX002: static (reachable) vs warmed key sets, both given
    as {infer_dtype: set(buckets)}. Each divergent key is a named
    finding."""
    findings = []
    for dt in sorted(set(static) | set(warmed)):
        reach = static.get(dt, set())
        warm = warmed.get(dt, set())
        # key=str: a set may mix int rungs with the fast lane's
        # '<rung>-row' key (ISSUE 14)
        for b in sorted(reach - warm, key=str):
            findings.append(Finding(
                "JX001", key_str(model, dt, fused_mode, b),
                f"bucket {b} is reachable (requests of <= {max_batch} "
                "rows can land in it) but warmup never compiles it — "
                "the first such request pays a steady-state XLA "
                "compile on the hot path"))
        for b in sorted(warm - reach, key=str):
            findings.append(Finding(
                "JX002", key_str(model, dt, fused_mode, b),
                f"bucket {b} is warmed but no admissible request size "
                f"(1..{max_batch}) can reach it — dead warmup cost on "
                "every load and swap"))
    return findings


# -- abstract forwards -----------------------------------------------------


def _build_model(model_name: str, cfg_dtype: str, fused_kernels: str):
    """The model exactly as build_model_and_mesh builds it, resolved
    against the CPU audit host (auto conv -> lax, auto fused -> XLA,
    pallas -> interpret — the canonical fingerprint basis)."""
    import jax.numpy as jnp

    from distributedmnist_tpu import models

    dtype = jnp.bfloat16 if cfg_dtype == "bfloat16" else jnp.float32
    return models.build(model_name, dtype=dtype, fused=fused_kernels,
                        platform="cpu", conv="auto")


def abstract_params(model):
    """The params tree as ShapeDtypeStructs — jax.eval_shape over
    model.init, zero device work (the registry's abstract_params
    discipline, minus the sharding)."""
    import jax
    import jax.numpy as jnp

    return jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, *_IMAGE_SHAPE)))["params"],
        jax.random.PRNGKey(0))


def _zeros_like_tree(shapes):
    import jax

    return jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), shapes)


def _avals_like_tree(tree):
    import jax

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        tree)


def abstract_forward(model, infer_dtype: str, fused_mode: str,
                     param_shapes) -> tuple:
    """(forward, params_avals) for one served precision — the same
    forward construction the engine jits (engine.py for float32,
    serve/quantize.py for the variants), minus the mesh-bound sharding
    constraint (device placement is not part of the audited graph).
    Variant preparation (quantization scales, folded normalization)
    runs over zero-valued host params: prep SHAPES are value-
    independent, and the prep tree is passed as a traced argument, so
    no weight bytes ever enter the jaxpr."""
    import jax.numpy as jnp

    if infer_dtype == "float32":
        dtype = getattr(model, "dtype", jnp.float32)

        def forward(p, x_u8):
            x = x_u8.astype(dtype) / 255.0
            return model.apply({"params": p}, x)

        return forward, param_shapes
    from distributedmnist_tpu.serve.quantize import prepare_inference

    prep, fast_forward = prepare_inference(
        model, _zeros_like_tree(param_shapes), infer_dtype, fused_mode)
    return fast_forward, _avals_like_tree(prep)


# -- jaxpr tracing, hazard scan, fingerprints ------------------------------


def trace_forward(fn: Callable, params_avals, bucket: int):
    """The abstract pass for one (forward, bucket): make_jaxpr over
    ShapeDtypeStructs under jax.transfer_guard('disallow') — no data,
    no device work, and any concrete transfer attempted mid-trace
    raises instead of silently staging."""
    import jax

    x_aval = jax.ShapeDtypeStruct((bucket, *_IMAGE_SHAPE), np.uint8)
    with jax.transfer_guard("disallow"):
        return jax.make_jaxpr(fn)(params_avals, x_aval)


def audit_jaxpr(jaxpr, key: str) -> list:
    """JX003/JX004 scan of one traced forward: captured host-array
    consts, weak-typed argument avals, float64 anywhere."""
    findings = []
    for c in jaxpr.consts:
        arr = np.asarray(c)
        if arr.size > 1:
            findings.append(Finding(
                "JX003", key,
                f"forward captures a host {arr.dtype} array of shape "
                f"{arr.shape} ({arr.nbytes} bytes) as a jaxpr const — "
                "host data must flow through the engine's staged "
                "device_put arguments, never a closure"))
        if arr.dtype in (np.float64, np.int64):
            findings.append(Finding(
                "JX004", key,
                f"const of dtype {arr.dtype} under the repo's "
                "disabled-x64 regime — silent 64-bit drift (truncated "
                "at trace time, split key under x64)"))
    for i, aval in enumerate(jaxpr.in_avals):
        if getattr(aval, "weak_type", False):
            findings.append(Finding(
                "JX004", key,
                f"argument {i} traces WEAK-TYPED ({aval.dtype}): a "
                "Python scalar reached the jitted call — the same "
                "call with a committed array compiles a second "
                "program for the same logical shape"))
        if np.dtype(aval.dtype) in (np.float64,):
            findings.append(Finding(
                "JX004", key,
                f"argument {i} has dtype float64 under disabled x64 — "
                "precision drift"))
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and np.dtype(dt) == np.float64:
                findings.append(Finding(
                    "JX004", key,
                    f"intermediate value of dtype float64 "
                    f"(primitive {eqn.primitive.name}) under disabled "
                    "x64 — f64 upcast drift"))
                break
    return findings


_ADDR_RE = None


def fingerprint(jaxpr) -> str:
    """Stable hash of the canonicalized jaxpr: the pretty-printed form
    (deterministic variable naming per trace) with whitespace runs
    collapsed and memory addresses scrubbed (custom_jvp eqn params
    print closure thunks as `<function ... at 0x...>` — process-random
    noise, not graph structure), sha256-truncated. Two traces of the
    same forward at the same avals produce the same string; any graph
    change — primitive, shape, dtype, parameter — changes it."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re

        _ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
    canon = _ADDR_RE.sub("0x0", " ".join(str(jaxpr).split()))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def audit_forward(fn: Callable, params_avals, bucket: int,
                  key: str) -> tuple:
    """(fingerprint, findings) for one forward at one bucket — the
    public per-forward entry the planted-hazard tests drive directly."""
    jaxpr = trace_forward(fn, params_avals, bucket)
    return fingerprint(jaxpr), audit_jaxpr(jaxpr, key)


def audit_row_forward(fn: Callable, params_avals, bucket: int,
                      key: str) -> tuple:
    """(fingerprint, findings) for the fast lane's row-staged program
    at one bucket (ISSUE 14): the engine's stage_row graph — write one
    row into the resident (bucket, 28, 28, 1) buffer on device, run
    the same forward — traced abstractly like any served forward."""
    import jax

    buf_aval = jax.ShapeDtypeStruct((bucket, *_IMAGE_SHAPE), np.uint8)
    row_aval = jax.ShapeDtypeStruct((1, *_IMAGE_SHAPE), np.uint8)

    def row_fn(p, buf, row):
        staged = jax.lax.dynamic_update_slice(buf, row, (0, 0, 0, 0))
        return fn(p, staged), staged

    with jax.transfer_guard("disallow"):
        jaxpr = jax.make_jaxpr(row_fn)(params_avals, buf_aval, row_aval)
    return fingerprint(jaxpr), audit_jaxpr(jaxpr, key)


def fingerprint_set_hash(fps: dict) -> str:
    """One hash over a whole {key: fingerprint} table — the
    compile-surface provenance stamp bench records carry."""
    canon = ";".join(f"{k}={v}" for k, v in sorted(fps.items()))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


# -- per-target audit ------------------------------------------------------


def audit_target(target: AuditTarget) -> dict:
    """The full audit of one deployment shape: trace every (dtype,
    bucket) forward, scan it, fingerprint it, and cross-check the
    static key universe against the warmup-derived warmed set."""
    from distributedmnist_tpu.ops import fused as fused_lib
    from distributedmnist_tpu.serve.engine import (fast_row_bucket,
                                                   make_buckets)
    from distributedmnist_tpu.serve.quantize import variant_supported

    mode = fused_lib.resolve(target.fused_kernels, "cpu")
    buckets = (tuple(sorted(set(target.buckets))) if target.buckets
               else make_buckets(target.serve_max_batch, target.n_chips))
    model = _build_model(target.model, target.dtype,
                         target.fused_kernels)
    param_shapes = abstract_params(model)
    # Per-model support filter (ISSUE 14): the megakernel variant
    # exists for the MLP only — the registry's auto-activation skips
    # an unsupported variant, so the audited universe must too (an
    # engine that can never be BUILT has no compile keys to audit).
    dtypes = tuple(dt for dt in dtype_universe(target.serve_infer_dtype)
                   if variant_supported(target.model, dt))

    findings: list = []
    fps: dict = {}
    reach = reachable_buckets(buckets, target.serve_max_batch)
    static = {dt: set(reach) for dt in dtypes}
    warmed = {dt: warmed_buckets(buckets, dt) for dt in dtypes}
    findings.extend(crosscheck_keys(target.model, mode, static, warmed,
                                    target.serve_max_batch))
    row_b = fast_row_bucket(buckets)
    for dt in dtypes:
        fn, avals = abstract_forward(model, dt, mode, param_shapes)
        for b in sorted(set(buckets)):
            k = key_str(target.model, dt, mode, b)
            try:
                fp, fnd = audit_forward(fn, avals, b, k)
            except Exception as e:
                findings.append(Finding(
                    "JX003", k,
                    "abstract trace failed under transfer_guard("
                    f"'disallow'): {type(e).__name__}: {e}"))
                continue
            fps[k] = fp
            findings.extend(fnd)
        if row_b is not None:
            # The fast lane's row-staged program (ISSUE 14): the same
            # forward behind an on-device dynamic_update_slice stage —
            # its own jit cache key, audited and fingerprinted like
            # any bucket rung (engine.py builds the identical graph).
            k = key_str(target.model, dt, mode, f"{row_b}-row")
            try:
                fp, fnd = audit_row_forward(fn, avals, row_b, k)
                fps[k] = fp
                findings.extend(fnd)
            except Exception as e:
                findings.append(Finding(
                    "JX003", k,
                    "abstract trace of the row-staged fast path "
                    "failed under transfer_guard('disallow'): "
                    f"{type(e).__name__}: {e}"))
    return {
        "label": target.label(),
        "model": target.model,
        "fused_mode": mode,
        "buckets": sorted(set(buckets)),
        "max_batch": target.serve_max_batch,
        "infer_dtypes": list(dtypes),
        "static_keys": sum(len(v) for v in static.values()),
        "warmed_keys": sum(len(v) for v in warmed.values()),
        "fingerprints": fps,
        "findings": findings,
    }


def train_step_fingerprints() -> tuple:
    """({key: fp}, findings): the training-step graphs train.py
    compiles, abstract-traced at the canonical geometry (1-device mesh,
    each model's preset optimizer, packed pixels, batch 512, one step
    per call) — a training-graph edit shows up in the snapshot gate
    exactly like a serving-forward edit."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import optim
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu import trainer

    mesh = make_mesh(jax.devices("cpu")[:1])
    fps: dict = {}
    findings: list = []
    presets = {"mlp": ("sgd", 0.1), "lenet": ("adam", 1e-3)}
    train_n, batch = 2048, 512
    for model_name, (opt, lr) in presets.items():
        model = _build_model(model_name, "float32", "auto")
        tx = optim.build(opt, lr, 0.9, flat=True)
        state_avals = jax.eval_shape(
            lambda k, m=model, t=tx: trainer.init_state(
                k, m, t, jnp.zeros((1, *_IMAGE_SHAPE))),
            jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, mode="auto",
                                       dtype=jnp.float32,
                                       pixel_format="packed")
        x_aval = jax.ShapeDtypeStruct((train_n, 196), np.int32)
        y_aval = jax.ShapeDtypeStruct((train_n,), np.int32)
        idx_aval = jax.ShapeDtypeStruct((1, batch), np.int32)
        k = f"{model_name}/train/{opt}/packed-b{batch}x1"
        try:
            with jax.transfer_guard("disallow"):
                jaxpr = jax.make_jaxpr(step)(state_avals, x_aval,
                                             y_aval, idx_aval)
        except Exception as e:
            findings.append(Finding(
                "JX003", k,
                "abstract trace of the train step failed under "
                f"transfer_guard('disallow'): {type(e).__name__}: {e}"))
            continue
        fps[k] = fingerprint(jaxpr)
        findings.extend(audit_jaxpr(jaxpr, k))
    return fps, findings


# -- snapshot gate ---------------------------------------------------------


_KEY_COMPONENTS = ("model", "infer_dtype", "fused_mode", "bucket")


def _describe_key_delta(k: str, pool: Sequence[str]) -> str:
    """Name the changed component when `k` differs from some key in
    `pool` in exactly one of (model, infer_dtype, fused_mode, bucket) —
    the changed-component naming the fingerprint-stability tests pin."""
    parts = k.split("/")
    for other in pool:
        op = other.split("/")
        if len(op) != len(parts):
            continue
        diffs = [i for i, (a, b) in enumerate(zip(parts, op)) if a != b]
        if len(diffs) == 1:
            i = diffs[0]
            name = (_KEY_COMPONENTS[i] if i < len(_KEY_COMPONENTS)
                    else f"component {i}")
            return (f" (differs from {other} in {name}: "
                    f"{op[i]} -> {parts[i]})")
    return ""


def diff_fingerprints(current: dict, snapshot: dict) -> list:
    """JX005 findings for every divergence between two {key: fp}
    tables: a changed fingerprint on a shared key names the forward as
    changed; an added/removed key names the key component that moved
    (bucket rung, dtype, fused mode, model) when one does."""
    findings = []
    for k in sorted(set(current) - set(snapshot)):
        findings.append(Finding(
            "JX005", k,
            "new compile key not in the snapshot"
            + _describe_key_delta(k, sorted(snapshot))
            + " — regenerate with --update-snapshots --reason '...'"))
    for k in sorted(set(snapshot) - set(current)):
        findings.append(Finding(
            "JX005", k,
            "snapshot key no longer produced by the audit"
            + _describe_key_delta(k, sorted(current))
            + " — regenerate with --update-snapshots --reason '...'"))
    for k in sorted(set(current) & set(snapshot)):
        if current[k] != snapshot[k]:
            findings.append(Finding(
                "JX005", k,
                f"compiled graph changed (fingerprint {snapshot[k]} -> "
                f"{current[k]}): the served forward itself was edited "
                "— regenerate with --update-snapshots --reason '...' "
                "stating why"))
    return findings


def load_snapshot(path: Optional[str] = None) -> Optional[dict]:
    path = path or snapshot_path()
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_snapshot(all_fps: dict, reason: str,
                   path: Optional[str] = None) -> str:
    """Persist {table_label: {key: fp}} with the stated reason — the
    regeneration paper trail the gate demands."""
    import time

    import jax

    path = path or snapshot_path()
    record = {
        "jax_version": jax.__version__,
        "reason": reason,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
        "fingerprints": all_fps,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


# -- the whole audit -------------------------------------------------------


def run_audit(targets: Optional[list] = None, with_train: bool = True,
              snapshot: str = "compare",
              snapshot_file: Optional[str] = None,
              partial: bool = False) -> dict:
    """The full gate pass. `snapshot` in {'compare', 'skip'}: compare
    raises no error when the snapshot file is missing (first bootstrap)
    or was written under a different jax version (graph printing may
    legitimately differ) — both downgrade to warnings; a PRESENT,
    same-version snapshot that diverges is JX005 findings. `partial`
    marks a deliberately narrowed audit (--models subset, --no-train):
    snapshot labels the audit never produced are then SKIPPED instead
    of read as removed keys — only the full default audit may declare
    a snapshot label dead."""
    import jax

    targets = default_targets() if targets is None else targets
    per_target = [audit_target(t) for t in targets]
    findings = [f for r in per_target for f in r["findings"]]
    all_fps = {r["label"]: r["fingerprints"] for r in per_target}
    if with_train:
        train_fps, train_findings = train_step_fingerprints()
        all_fps["train"] = train_fps
        findings.extend(train_findings)
    warnings: list = []
    if snapshot == "compare":
        snap = load_snapshot(snapshot_file)
        if snap is None:
            warnings.append(
                "no fingerprint snapshot found — bootstrap with "
                "--update-snapshots --reason 'initial snapshot'")
        elif snap.get("jax_version") != jax.__version__:
            warnings.append(
                f"snapshot was written under jax "
                f"{snap.get('jax_version')}, this host runs "
                f"{jax.__version__} — fingerprint comparison skipped "
                "(jaxpr printing may legitimately differ across "
                "versions); regenerate to re-arm the gate")
        else:
            snap_fps = snap.get("fingerprints", {})
            labels = (sorted(all_fps) if partial
                      else sorted(set(all_fps) | set(snap_fps)))
            for label in labels:
                findings.extend(diff_fingerprints(
                    all_fps.get(label, {}), snap_fps.get(label, {})))
    static_total = sum(r["static_keys"] for r in per_target)
    warmed_total = sum(r["warmed_keys"] for r in per_target)
    return {
        "kind": "jaxcheck",
        "jax_version": jax.__version__,
        "targets": [
            {k: v for k, v in r.items() if k != "findings"}
            for r in per_target],
        "static_keys_total": static_total,
        "warmed_keys_total": warmed_total,
        "fingerprint_set_hash": fingerprint_set_hash(
            {f"{lbl}:{k}": v for lbl, fps in all_fps.items()
             for k, v in fps.items()}),
        "fingerprints": all_fps,
        "findings": findings,
        "warnings": warnings,
        "closed": not findings,
    }


def compile_surface_summary(model: str, buckets: Sequence[int],
                            max_batch: int, infer_dtype: str,
                            fused_kernels: str = "auto",
                            cfg_dtype: str = "float32") -> dict:
    """The compile-surface provenance block bench records carry
    (ISSUE 12 satellite): static key count + fingerprint-set hash for
    ONE deployment geometry at its headline serving precision — cheap
    (a couple dozen abstract traces), and enough for --baseline to
    refuse comparing records whose compiled surfaces differ silently."""
    target = AuditTarget(
        model=model, serve_max_batch=max_batch, n_chips=1,
        serve_infer_dtype=infer_dtype, fused_kernels=fused_kernels,
        dtype=cfg_dtype, buckets=tuple(buckets))
    r = audit_target(target)
    import jax

    return {
        "static_keys": r["static_keys"],
        "fingerprint_set_hash": fingerprint_set_hash(r["fingerprints"]),
        "infer_dtypes": r["infer_dtypes"],
        "fused_mode": r["fused_mode"],
        "jax_version": jax.__version__,
        "findings": len(r["findings"]),
    }


# -- CLI -------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedmnist_tpu.analysis.jaxcheck",
        description="Static compile-surface auditor: abstract-evaluate "
                    "every forward the serving registry could dispatch, "
                    "prove the jit cache-key universe closed (warmed == "
                    "reachable), transfer-clean and weak-type-free, and "
                    "gate the jaxpr fingerprints against the committed "
                    "snapshot. Exit 0 clean, 1 findings, 2 internal "
                    "error.")
    p.add_argument("--models", default="mlp,lenet",
                   help="comma-separated models to audit (default both)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the JX rule table and exit")
    p.add_argument("--no-train", action="store_true",
                   help="skip the training-step fingerprints")
    p.add_argument("--no-snapshot", action="store_true",
                   help="skip the fingerprint snapshot gate")
    p.add_argument("--update-snapshots", action="store_true",
                   help="regenerate analysis/jaxpr_fingerprints.json "
                        "from this audit (requires --reason)")
    p.add_argument("--reason", default=None,
                   help="[--update-snapshots] WHY the compiled surface "
                        "changed — recorded in the snapshot")
    p.add_argument("--emit", action="store_true",
                   help="write an ANALYSIS_r*.json round artifact "
                        "(BENCH-style numbering; also triggered by "
                        "DMNIST_JAXCHECK_ARTIFACT=1)")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, (summary, why) in sorted(RULES.items()):
            print(f"{rule}  {summary}\n        {why}")
        return 0
    if args.update_snapshots and not args.reason:
        print("jaxcheck: --update-snapshots requires --reason '...' — "
              "a regenerated surface without a stated why is exactly "
              "the silent drift the gate exists to catch",
              file=sys.stderr)
        return 2
    models = [m.strip() for m in args.models.split(",") if m.strip()]
    unknown = [m for m in models if m not in ("mlp", "lenet")]
    if unknown:
        print(f"jaxcheck: unknown model(s) {unknown}", file=sys.stderr)
        return 2
    targets = [t for t in default_targets() if t.model in models]
    # A narrowed audit (one model, or --no-train) still gates the
    # labels it covers, but must neither read the snapshot's OTHER
    # labels as removed keys nor overwrite them on --update-snapshots.
    partial = args.no_train or set(models) != {"mlp", "lenet"}
    if args.update_snapshots and partial:
        import jax

        existing = load_snapshot()
        if (existing is not None
                and existing.get("jax_version") != jax.__version__):
            print("jaxcheck: refusing a PARTIAL --update-snapshots "
                  "over a snapshot written under jax "
                  f"{existing.get('jax_version')} (this host runs "
                  f"{jax.__version__}): merging would stamp the "
                  "snapshot with the new version while the unaudited "
                  "labels still carry the old version's jaxpr "
                  "printing, re-arming the JX005 gate against them — "
                  "run a FULL --update-snapshots instead",
                  file=sys.stderr)
            return 2
    try:
        report = run_audit(
            targets, with_train=not args.no_train,
            snapshot="skip" if (args.no_snapshot
                               or args.update_snapshots) else "compare",
            partial=partial)
    except Exception as e:     # a broken auditor must never read clean
        print(f"jaxcheck: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.update_snapshots:
        fps = report["fingerprints"]
        if partial:
            existing = load_snapshot()
            merged = dict((existing or {}).get("fingerprints", {}))
            merged.update(fps)
            fps = merged
        path = write_snapshot(fps, args.reason)
        print(f"jaxcheck: snapshot regenerated at {path} "
              f"({'partial audit merged into existing labels' if partial else 'full surface'}; "
              f"reason: {args.reason})")

    for w in report["warnings"]:
        print(f"jaxcheck: WARNING: {w}", file=sys.stderr)
    for f in sorted(report["findings"],
                    key=lambda f: (f.rule, f.key)):
        print(f.format())
    n = len(report["findings"])
    print(f"jaxcheck: {len(report['targets'])} target(s), "
          f"{report['static_keys_total']} static keys / "
          f"{report['warmed_keys_total']} warmed, fingerprint set "
          f"{report['fingerprint_set_hash']} — "
          f"{'CLOSED, 0 findings' if n == 0 else f'{n} finding(s)'}",
          file=sys.stderr)

    if args.emit or os.environ.get("DMNIST_JAXCHECK_ARTIFACT"):
        from distributedmnist_tpu.analysis import report as report_mod

        payload = dict(report)
        payload["findings"] = [dataclasses.asdict(f)
                               for f in report["findings"]]
        path = report_mod.emit_analysis(payload)
        print(f"jaxcheck: artifact written to {path}")
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
