"""Concurrency sanitizer, project lint, and deterministic model
checker (ISSUEs 8 + 11).

The serving stack is a ~5k-LoC concurrent system whose invariants —
lock acquisition order, nothing slow under a hot-path lock, every
staging buffer recycled, every in-flight slot released, no schedule in
which a promote races a single-flight insert — were enforced by
reviewer vigilance through PRs 3-7 (each needed multiple post-review
hardening rounds for the same recurring bug classes). This package
enforces them mechanically, on every tier-1 run:

- locks.py     the named Lock/RLock/Condition/Semaphore/FIFO/Thread
               factory every serve/ module constructs its primitives
               through. With nothing installed the factories return
               the bare threading primitives (zero wrappers, zero
               cost); under a sanitizer they return instrumented
               wrappers; under a model-checking Controller they return
               shadow primitives whose every operation is a schedule
               yield point.
- sanitize.py  the runtime sanitizer: a global lock-order graph with
               cycle detection (potential deadlock), blocking-call-
               under-lock detection (time.sleep / socket I/O / the
               device->host sync while holding a hot-path lock), and
               resource-balance accounting (staging-pool checkouts and
               in-flight window slots must net to zero at drain).
               Opt-in via install_sanitizer() or DMNIST_SANITIZE=1; a
               conftest fixture turns it on for every serve test.
- explore.py   the deterministic schedule explorer (ISSUE 11): a
               loom/CHESS-style controller that runs threads one-at-a-
               time through the factory yield points under a chosen
               schedule — seeded-random or bounded systematic DFS with
               sleep-set partial-order reduction on independent
               primitive names — so an interleaving bug is a
               REPLAYABLE SEED, not a flake. `python -m
               distributedmnist_tpu.analysis.explore` (tier-1 runs
               --smoke; scripts/explore.sh the 500-schedule budget).
- harnesses.py the four explored serve state machines (cache single-
               flight vs promote epoch, registry promote/rollback/
               eviction, batcher submit/shed/drain/stop, fleet pick/
               failover/drain-rejoin) with their invariants, plus the
               planted mutations the explorer must find (self-test).
- report.py    ANALYSIS_r*.json round artifacts (BENCH-style
               numbering) emitted by the explorer CLI and by
               Sanitizer.assert_clean(artifact=...) — the analysis-
               coverage trajectory.
- lint.py      the AST project lint (`python -m
               distributedmnist_tpu.analysis`): codified rules from
               past review findings, each with a rule ID, a file:line
               report, and a pragma allowlist — including the
               dataflow-aware DML009 (future resolution reachable
               under a serve lock, interprocedural), DML010 (lock-
               containment inference) and DML011 (jit-cache-key
               hazards). Exits nonzero on findings — scripts/lint.sh
               wires it before pytest in scripts/tier1.sh.
"""

from distributedmnist_tpu.analysis.locks import (make_condition,  # noqa: F401
                                                 make_fifo, make_lock,
                                                 make_rlock,
                                                 make_semaphore,
                                                 make_thread)
from distributedmnist_tpu.analysis.sanitize import (  # noqa: F401
    Sanitizer, active_sanitizer, blocking, install_sanitizer,
    resource_acquire, resource_release, uninstall_sanitizer)

__all__ = [
    "make_lock", "make_rlock", "make_condition", "make_semaphore",
    "make_fifo", "make_thread", "Sanitizer", "install_sanitizer",
    "uninstall_sanitizer", "active_sanitizer", "blocking",
    "resource_acquire", "resource_release",
]
