"""Concurrency sanitizer + project lint (ISSUE 8).

The serving stack is a ~4.8k-LoC concurrent system whose invariants —
lock acquisition order, nothing slow under a hot-path lock, every
staging buffer recycled, every in-flight slot released — were enforced
by reviewer vigilance through PRs 3-7 (each needed multiple post-review
hardening rounds for the same recurring bug classes). This package
enforces them mechanically, on every tier-1 run:

- locks.py     the named Lock/RLock/Condition/Semaphore/Thread factory
               every serve/ module constructs its primitives through.
               With no sanitizer installed the factories return the
               bare threading primitives (zero wrappers, zero cost);
               installed, they return instrumented wrappers feeding the
               sanitizer.
- sanitize.py  the runtime sanitizer: a global lock-order graph with
               cycle detection (potential deadlock), blocking-call-
               under-lock detection (time.sleep / socket I/O / the
               device->host sync while holding a hot-path lock), and
               resource-balance accounting (staging-pool checkouts and
               in-flight window slots must net to zero at drain).
               Opt-in via install_sanitizer() or DMNIST_SANITIZE=1; a
               conftest fixture turns it on for every serve test.
- lint.py      the AST project lint (`python -m
               distributedmnist_tpu.analysis`): codified rules from
               past review findings, each with a rule ID, a file:line
               report, and a pragma allowlist. Exits nonzero on
               findings — scripts/lint.sh wires it before pytest in
               scripts/tier1.sh.
"""

from distributedmnist_tpu.analysis.locks import (make_condition,  # noqa: F401
                                                 make_lock, make_rlock,
                                                 make_semaphore,
                                                 make_thread)
from distributedmnist_tpu.analysis.sanitize import (  # noqa: F401
    Sanitizer, active_sanitizer, blocking, install_sanitizer,
    resource_acquire, resource_release, uninstall_sanitizer)

__all__ = [
    "make_lock", "make_rlock", "make_condition", "make_semaphore",
    "make_thread", "Sanitizer", "install_sanitizer",
    "uninstall_sanitizer", "active_sanitizer", "blocking",
    "resource_acquire", "resource_release",
]
