"""AST project lint (ISSUE 8): codified rules from the serving stack's
recurring review findings, run as `python -m distributedmnist_tpu.analysis`
(scripts/lint.sh wires it before pytest in scripts/tier1.sh).

Every rule encodes a bug class a past PR shipped and a review round had
to catch by hand; the lint makes the catch mechanical. Rules report
`path:line RULE message` and the CLI exits 1 on any finding, 0 clean.

Allowlist: a finding whose line (or the line above it) carries
`# lint: allow[RULE] <reason>` is suppressed — the reason is REQUIRED
(a bare pragma does not suppress; silent exemptions rot). Allowed
findings are counted and printable with --show-allowed.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import re
import sys
from typing import Iterable, Optional

# Rule registry: ID -> (summary, the historical bug it encodes).
RULES = {
    "DML001": (
        "bare threading primitive in the serving stack",
        "serve/ code must construct Lock/RLock/Condition/Semaphore via "
        "analysis.locks.make_* so the sanitizer can name it in the "
        "lock-order graph (PRs 3-6 each hand-audited lock nesting; a "
        "bare primitive is invisible to the deadlock check)"),
    "DML002": (
        "bare threading.Thread in the serving stack",
        "serve/, serve.py and bench.py must spawn via "
        "analysis.locks.make_thread(name=..., daemon=...) — review "
        "rounds repeatedly caught threads that forgot daemon=True and "
        "stranded pytest at exit (the conftest thread-hygiene fixture's "
        "bug class, moved to construction time)"),
    "DML003": (
        "failpoint name not in the faults.py registry",
        "a typo'd failpoint/spec string silently injects NOTHING and a "
        "chaos drill then 'proves' resilience it never exercised — "
        "parse_spec rejects unknown names at runtime (PR 5 hardening); "
        "this rule rejects them at lint time, including in tests and "
        "the bench's programmatic chaos schedules"),
    "DML004": (
        "time.time() in serving/bench code",
        "latency and elapsed-time math must use the monotonic clock: a "
        "wall-clock step (NTP, manual set) corrupts every derived "
        "latency/uptime/ordering value. Wall-clock display stamps are "
        "fine — allowlist them with a reason"),
    "DML005": (
        "jax.jit outside engine/warmup construction paths",
        "the zero-recompile serving contract holds because every "
        "compiled program is built (and warmed) in engine.py/"
        "quantize.py; a jit call anywhere else in serve/ is a "
        "steady-state recompile hazard the compile-counter tests "
        "cannot attribute"),
    "DML006": (
        "staging-pool recycle not inside a finally block",
        "the PR 5 leak: engine.fetch recycled its pooled buffer only on "
        "success, so a fetch-failure storm bled one buffer per failed "
        "batch — every _staging_pool append must sit in try/finally"),
    "DML007": (
        "trace span begun without a try/finally end",
        "an exception mid-stage must not leave an unclosed span skewing "
        "attribution (ISSUE 9): every begin_span() in serve/+serve.py "
        "must be the statement immediately before a try whose finally "
        "calls end_span(). Spans that end on another thread are "
        "synthesized closed via add_span from monotonic stamps instead "
        "— begin_span is strictly same-thread"),
    "DML008": (
        "cache state mutated outside the cache's named lock",
        "the prediction cache's LRU table and single-flight flight "
        "registry (_entries/_flights — ISSUE 10) are mutated from "
        "submit threads, the completion thread's done-callbacks AND "
        "the registry's invalidation hook; any mutation outside a "
        "`with <...>_lock:` block is a torn-LRU / double-resolved-"
        "follower race the sanitizer can only catch if it happens to "
        "fire — the lint rejects the shape outright"),
    "DML009": (
        "Future resolution reachable while a serve lock is held",
        "set_result/set_exception run done-callbacks INLINE on the "
        "resolving thread (the cache front's single-flight fan-out "
        "among them): resolving under a lock stalls every concurrent "
        "path through it and silently orders that lock under whatever "
        "the callbacks take — the batcher.stop(drain=False) shape "
        "fixed in ISSUE 11, checked interprocedurally over one module "
        "(a helper whose every call site holds the lock counts as "
        "under it)"),
    "DML010": (
        "shared-field mutation outside its inferred guarding lock",
        "lock-containment INFERENCE generalizing DML008 beyond the "
        "cache's two containers: when >= 2 mutation sites of a field "
        "hold one common named lock (registry._state's version table, "
        "the fleet pick-lock's _Replica accounting), a lock-free "
        "mutation site of the same field is a torn-state race the "
        "sanitizer can only catch if the schedule happens to expose "
        "it — the model checker's static sibling (ISSUE 11)"),
    "DML011": (
        "jit-cache-key hazard: thread-local device pin / non-hashable "
        "static arg",
        "jax.default_device is THREAD-LOCAL and part of the jit cache "
        "key — warmup pinned on one thread leaves every worker thread "
        "cold (the dryrun serve-reload zero-recompile trap), a "
        "steady-state recompile the compile-counter tests cannot "
        "attribute; and a mutable-literal static arg cannot be hashed "
        "into the cache key at all (TypeError at first call). Caught "
        "statically in serving/bench code (ISSUE 11)"),
    "DML012": (
        "implicit host->device array conversion in serve/ outside the "
        "engine staging path",
        "np arrays flow onto the device ONLY through engine.py's "
        "pooled staging + device_put discipline (and quantize.py's "
        "build-time weight preparation): a jnp.array/jnp.asarray/"
        "jax.device_put anywhere else in serve/ is an implicit "
        "per-call host->device transfer the staging pool, the "
        "transfer audit (analysis/jaxcheck.py JX003) and the compile "
        "counter all cannot attribute. Build/load-time placements are "
        "allowlisted with a reason (ISSUE 12)"),
    "DML013": (
        "Python scalar literal at a jitted call site (weak-type "
        "cache-key split)",
        "a bare int/float literal passed to a jitted function traces "
        "WEAK-TYPED: the same call later made with a committed array "
        "or np scalar compiles a SECOND program for the same logical "
        "shape — a silent jit cache-key split the compile counter "
        "attributes to nothing (jaxcheck JX004 is the abstract-pass "
        "sibling; DML011 covers the static-arg shapes). Pass arrays/"
        "np scalars, or make the argument static (ISSUE 12)"),
    "DML015": (
        "engine dispatch/infer call in serve/ outside the "
        "lane-deciding dispatch plumbing",
        "every dispatch must pass the batcher's lane decision (and the "
        "router/fleet plumbing under it) so metrics populations, trace "
        "spans, failpoints and the resilience outcomes are NEVER "
        "skipped — a direct engine.infer()/dispatch()/dispatch_fast() "
        "call from any other serve/ module is an invisible request "
        "path the whole observability/chaos story silently misses "
        "(ISSUE 14; admin-path uses like the registry's parity gate "
        "are allowlisted with a reason)"),
    "DML014": (
        "failpoint declared but exercised by no test or chaos spec",
        "untested failure handling is indistinguishable from none "
        "(PR 5's own rule): every faults.KNOWN_FAILPOINTS name must "
        "be exercised by at least one test or named in a chaos spec "
        "string somewhere in the repo — a dead name is either a "
        "coverage hole a chaos drill silently skips, or a stale "
        "weave. Coverage asserted as a static cross-check over the "
        "whole repo (ISSUE 12)"),
    "DML016": (
        "confidence-policy fork: margin read or hardcoded confidence "
        "constant outside the cascade's calibrated threshold",
        "the cascade's escalation decision is justified by exactly one "
        "thing — the composed-accuracy gate that calibrated the "
        "threshold (ISSUE 17, PARITY.md). A serve/ code path that "
        "reads per-row softmax margins outside cascade.py, or "
        "compares a margin against a numeric literal, has forked the "
        "confidence policy: its routing decisions are judged by NO "
        "gate and silently drift from the accuracy bar the operator "
        "was promised. All margin decisions route through "
        "cascade.threshold_of (the one accessor)"),
    "DML017": (
        "tenancy scheduler state mutated outside the scheduler's "
        "named lock",
        "the global scheduler's admission/fairness accounting (token "
        "buckets, DRR deficits and skip counters, per-tenant queues "
        "and pending-row totals, the ring cursor) is one atomically-"
        "consistent decision state: a lock-free mutation of any of it "
        "in serve/ tears a grant decision mid-flight — quota double-"
        "spends, deficit drift that silently breaks the asserted "
        "starvation bound, queues whose row accounting disagrees with "
        "their contents. DML010's inference needs >= 2 locked sites "
        "to learn a guard; these fields are DECLARED guarded (ISSUE "
        "18), so even a single bare mutation site is a finding"),
    "DML018": (
        "cluster epoch mutated outside the promote fan-out path",
        "the cluster epoch is the fleet-wide serialization token for "
        "version visibility (ISSUE 19): the gateway bumps it only "
        "inside the two-phase promote flip (pause, drain, promote-"
        "all, fan out), and a worker adopts it only through the "
        "/cluster/epoch receiving end. Any other assignment — a "
        "handler 'fixing' a stale stamp, a test helper poking the "
        "field, a second admin path — moves the epoch without the "
        "barrier and re-opens exactly the mixed-version window the "
        "gateway exists to close (a reply stamped ahead of or behind "
        "its admission epoch). Allowed writers: __init__/"
        "__post_init__ construction, Gateway.promote_fanout, and the "
        "worker-side apply_cluster_epoch"),
    "DML019": (
        "autoscale actuation called outside the Autoscaler's "
        "actuator path",
        "the serving stack has exactly ONE capacity-actuation surface "
        "(ISSUE 20): batcher.apply_scale (in-flight window + bucket "
        "ceiling) and gateway.add_worker/drain_worker (fleet "
        "membership), called only from an Actuator's scale_to. A "
        "second caller — a handler 'helpfully' widening the window, "
        "a drill script draining workers directly — races the "
        "control loop's read-decide-actuate cycle and un-prices its "
        "chip-second accounting: the loop's action log would claim a "
        "scale the system does not have. Allowed caller: scale_to "
        "(WindowActuator/GatewayActuator)"),
}

_PRAGMA_RE = re.compile(r"lint:\s*allow\[(DML\d{3})\]\s*(\S.*)?")
_FAILPOINT_NAME_RE = re.compile(r"^[a-z_]+\.[a-z_]+$")
# A string constant that LOOKS like a fault spec fragment: a failpoint
# name, a colon, and at least one key=value — the shape bench's
# programmatic chaos schedules concatenate.
_SPEC_SHAPED_RE = re.compile(r"^;?[a-z_]+\.[a-z_]+:[^;]*=")

_BARE_PRIMITIVES = frozenset(
    ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"))

# DML008: the prediction cache's mutable state containers (ISSUE 10)
# and the attribute-call names that mutate a dict/OrderedDict. Reads
# (.get, .items, len) are free; anything here must sit under the
# cache's named lock.
_CACHE_STATE_ATTRS = frozenset(("_entries", "_flights"))
# DML017: the global scheduler's tenancy accounting (ISSUE 18) —
# DECLARED guarded by the scheduler's named condition, not inferred
# like DML010 (inference needs two locked sites; a brand-new counter
# with one bare mutation site would sail through it). Attribute names
# chosen to be unique to serve/tenancy.py within serve/.
_TENANCY_STATE_ATTRS = frozenset(
    ("_tokens", "_deficits", "_skips", "_granted", "_pending_rows",
     "_queues", "_cursor"))
_MUTATING_METHODS = frozenset(
    ("pop", "popitem", "clear", "setdefault", "update", "move_to_end",
     "append"))


@dataclasses.dataclass
class Finding:
    path: str                     # repo-relative, posix separators
    line: int
    rule: str
    message: str
    allowed: bool = False
    allow_reason: Optional[str] = None

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


# -- scopes ----------------------------------------------------------------

def _in_serve_pkg(rel: str) -> bool:
    return rel.startswith("distributedmnist_tpu/serve/")


def _primitive_scope(rel: str) -> bool:
    return _in_serve_pkg(rel) or rel == "serve.py"


def _thread_scope(rel: str) -> bool:
    return _in_serve_pkg(rel) or rel in ("serve.py", "bench.py")


def _time_scope(rel: str) -> bool:
    return _in_serve_pkg(rel) or rel in ("serve.py", "bench.py")


def _jit_scope(rel: str) -> bool:
    return (_in_serve_pkg(rel)
            and os.path.basename(rel) not in ("engine.py", "quantize.py"))


def _failpoint_scope(rel: str) -> bool:
    return True


def _span_scope(rel: str) -> bool:
    # trace.py is the facility (its module-level begin_span delegates
    # to the active tracer) — the rule polices the CALL sites.
    return (_primitive_scope(rel)
            and os.path.basename(rel) != "trace.py")


# -- helpers ---------------------------------------------------------------

def _known_failpoints() -> frozenset:
    from distributedmnist_tpu.serve.faults import KNOWN_FAILPOINTS

    return frozenset(KNOWN_FAILPOINTS)


def _docstring_nodes(tree: ast.AST) -> set:
    """ids of Constant nodes that are docstrings (prose mentions of
    failpoint names in docs are not spec strings)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                out.add(id(body[0].value))
    return out


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _spec_segment_names(s: str) -> list:
    """Failpoint names referenced by a spec-shaped string (possibly a
    fragment of a larger concatenated/f-string spec)."""
    names = []
    for seg in s.split(";"):
        seg = seg.strip()
        if not seg or ":" not in seg:
            continue
        name = seg.partition(":")[0].strip()
        if _FAILPOINT_NAME_RE.match(name):
            names.append(name)
    return names


# -- dataflow machinery (DML009 / DML010) ----------------------------------
#
# Both rules need the same two ingredients, computed per module:
#
# 1. a LEXICAL lock context per statement — which named locks (attrs/
#    vars bound from make_lock/make_rlock/make_condition, or anything
#    the `_lock`-suffix convention names) are held via enclosing
#    `with` blocks, with nested function/lambda bodies excluded (a
#    callback DEFINED under a lock does not RUN under it);
# 2. an INTERPROCEDURAL "always held" set per function — the
#    intersection of the effective lock context over every local call
#    site (`self.f()` / bare `f()`), iterated to fixpoint, so a helper
#    like registry._evict_locked whose every caller holds _state is
#    analyzed as under _state even though its own body has no `with`.

_FUTURE_RESOLVERS = frozenset(("set_result", "set_exception"))
_MUTATING_METHODS_ANY = _MUTATING_METHODS | frozenset(
    ("appendleft", "extend", "insert", "add", "discard", "remove",
     "popleft", "rotate"))
_LOCK_FACTORIES = frozenset(("make_lock", "make_rlock",
                             "make_condition"))


@dataclasses.dataclass
class _FuncFlow:
    """One function's lock-relevant facts. Functions are keyed by a
    CLASS-QUALIFIED name ('Registry.promote', bare for module level) so
    same-named methods of different classes never conflate — a lock-free
    `Y.finish()` must not inherit `X.finish()`'s Future resolution."""

    name: str
    cls: Optional[str] = None
    resolves: list = dataclasses.field(default_factory=list)
    # (lineno, lexical locks) of direct .set_result/.set_exception
    calls: list = dataclasses.field(default_factory=list)
    # raw: (kind 'self'|'bare', callee shortname, lineno, lexical locks);
    # _collect_flows resolves these to qualified callee names
    mutations: list = dataclasses.field(default_factory=list)
    # (attr, lineno, lexical locks, description, receiver-is-self)


def _lock_attr_names(tree: ast.AST) -> frozenset:
    """Names bound from the lock factories — the module's lock
    vocabulary ('_state', '_admin', '_cond', a local 'cv', ...)."""
    names = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _call_name(node.value.func) in _LOCK_FACTORIES):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    names.add(t.attr)
                elif isinstance(t, ast.Name):
                    names.add(t.id)
    return frozenset(names)


def _lock_token(expr: ast.AST, lock_names: frozenset) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        if expr.attr in lock_names or expr.attr.endswith("_lock"):
            return expr.attr
    elif isinstance(expr, ast.Name):
        if expr.id in lock_names or expr.id.endswith("_lock"):
            return expr.id
    return None


def _base_attr(e: ast.AST, lock_names: frozenset) -> Optional[str]:
    """The field an expression mutates, seen through subscripts:
    `self._versions[k]` -> '_versions'. Thread-local state (receiver
    chain through `_tls`) is per-thread by construction and exempt;
    lock objects themselves are not 'fields'."""
    while isinstance(e, ast.Subscript):
        e = e.value
    if not isinstance(e, ast.Attribute):
        return None
    v = e.value
    if isinstance(v, ast.Attribute) and v.attr == "_tls":
        return None
    if isinstance(v, ast.Name) and v.id in ("_tls", "tls"):
        return None
    attr = e.attr
    if attr in lock_names or attr.endswith("_lock"):
        return None
    return attr


def _recv_is_self(e: ast.AST) -> bool:
    """True when the mutated field hangs directly off self/cls (so it
    belongs to the enclosing class); `replica.windows` or
    `self._replicas[r].q` mutate ANOTHER object's field and stay in the
    module-wide bucket."""
    while isinstance(e, ast.Subscript):
        e = e.value
    if isinstance(e, ast.Attribute):
        v = e.value
        return isinstance(v, ast.Name) and v.id in ("self", "cls")
    return False


def _walk_exec(node: ast.AST, held: frozenset, flow: _FuncFlow,
               lock_names: frozenset) -> None:
    """Record calls/resolves/mutations with their lexical lock context;
    nested function and lambda bodies are separate execution scopes."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return
    if isinstance(node, ast.With):
        tokens = {t for t in (
            _lock_token(item.context_expr, lock_names)
            for item in node.items) if t}
        for item in node.items:
            _walk_exec(item.context_expr, held, flow, lock_names)
        inner = held | frozenset(tokens)
        for stmt in node.body:
            _walk_exec(stmt, inner, flow, lock_names)
        return
    if isinstance(node, ast.Call):
        func = node.func
        cname = _call_name(func)
        if (cname in _FUTURE_RESOLVERS
                and isinstance(func, ast.Attribute)):
            flow.resolves.append((node.lineno, held))
        if isinstance(func, ast.Name):
            flow.calls.append(("bare", func.id, node.lineno, held))
        elif (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls")):
            flow.calls.append(("self", func.attr, node.lineno, held))
        if (isinstance(func, ast.Attribute)
                and func.attr in _MUTATING_METHODS_ANY):
            attr = _base_attr(func.value, lock_names)
            if attr:
                flow.mutations.append(
                    (attr, node.lineno, held, f"{attr}.{func.attr}()",
                     _recv_is_self(func.value)))
    elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for el in elts:
                if isinstance(el, ast.Subscript):
                    attr = _base_attr(el, lock_names)
                    desc = f"{attr}[...] = ..." if attr else None
                elif isinstance(el, ast.Attribute):
                    attr = _base_attr(el, lock_names)
                    desc = f"{attr} = ..." if attr else None
                else:
                    attr = desc = None
                if attr:
                    flow.mutations.append(
                        (attr, node.lineno, held, desc,
                         _recv_is_self(el)))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            attr = _base_attr(t, lock_names)
            if attr:
                flow.mutations.append(
                    (attr, node.lineno, held, f"del {attr}[...]",
                     _recv_is_self(t)))
    for child in ast.iter_child_nodes(node):
        _walk_exec(child, held, flow, lock_names)


def _collect_flows(tree: ast.AST, lock_names: frozenset) -> list:
    flows = []

    def visit(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = f"{cls}.{child.name}" if cls else child.name
                flow = _FuncFlow(qual, cls=cls)
                for stmt in child.body:
                    _walk_exec(stmt, frozenset(), flow, lock_names)
                flows.append(flow)
                # nested defs close over self, so they keep the class
                # context (a nested def in a method calling self.f()
                # targets the same class)
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, None)
    names = {f.name for f in flows}
    for f in flows:
        resolved = []
        for kind, callee, lineno, held in f.calls:
            if kind == "self":
                target = f"{f.cls}.{callee}" if f.cls else callee
            elif callee in names:          # bare: module-level first
                target = callee
            elif f.cls and f"{f.cls}.{callee}" in names:
                target = f"{f.cls}.{callee}"   # nested def in a method
            else:
                target = callee
            resolved.append((target, lineno, held))
        f.calls = resolved
    return flows


def _always_held(flows: list) -> dict:
    """Function name -> locks held at EVERY local call site (effective:
    the caller's own always-held set is included), to fixpoint. A
    function with no local call sites is a public entry — nothing is
    known to be held."""
    names = {f.name for f in flows}
    always = {n: frozenset() for n in names}
    for _ in range(5):
        incoming: dict = {n: None for n in names}
        for f in flows:
            base = always[f.name]
            for callee, _lineno, held in f.calls:
                if callee in names:
                    eff = held | base
                    cur = incoming[callee]
                    incoming[callee] = (eff if cur is None
                                        else cur & eff)
        new = {n: (incoming[n] if incoming[n] is not None
                   else frozenset()) for n in names}
        if new == always:
            break
        always = new
    return always


def _check_dml009(flows: list, always: dict, rel: str,
                  findings: list) -> None:
    names = {f.name for f in flows}
    reaches = {f.name for f in flows if f.resolves}
    changed = True
    while changed:
        changed = False
        for f in flows:
            if f.name in reaches:
                continue
            if any(c in reaches for c, _, _ in f.calls):
                reaches.add(f.name)
                changed = True
    for f in flows:
        base = always[f.name]
        for lineno, held in f.resolves:
            eff = held | base
            if eff:
                findings.append(Finding(
                    rel, lineno, "DML009",
                    "future resolved while holding "
                    f"{sorted(eff)} — done-callbacks run inline on "
                    "this thread (the single-flight fan-out among "
                    "them): move the set_result/set_exception outside "
                    "the lock (collect under it, resolve after)"))
        for callee, lineno, held in f.calls:
            eff = held | base
            if (eff and callee in reaches and callee in names
                    and callee != f.name and not always[callee]):
                findings.append(Finding(
                    rel, lineno, "DML009",
                    f"call to {callee}() while holding {sorted(eff)} — "
                    "it (transitively) resolves a Future, whose done-"
                    "callbacks would then run under the lock"))


def _check_dml010(flows: list, always: dict, rel: str,
                  findings: list) -> None:
    sites: dict = {}
    for f in flows:
        if f.name.split(".")[-1] in ("__init__", "__post_init__"):
            continue
        base = always[f.name]
        for attr, lineno, held, desc, is_self in f.mutations:
            # self-fields are per-class (same-named fields of two
            # classes are DIFFERENT state); other receivers (`rep.q`,
            # `self._replicas[r].windows`) pool module-wide — the
            # fleet _Replica-fields class
            owner = f.cls if is_self else None
            sites.setdefault((owner, attr), []).append(
                (lineno, held | base, desc))
    for (_owner, attr), lst in sorted(
            sites.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])):
        locked = [s for s in lst if s[1]]
        bare = [s for s in lst if not s[1]]
        if len(locked) < 2 or not bare:
            continue
        guard = frozenset.intersection(*[s[1] for s in locked])
        if not guard:
            continue          # no single consistent guard — ambiguous
        gname = "/".join(sorted(guard))
        for lineno, _eff, desc in bare:
            findings.append(Finding(
                rel, lineno, "DML010",
                f"mutation `{desc}` outside inferred guard "
                f"`{gname}` — {len(locked)} other mutation site(s) of "
                f"`{attr}` in this module hold it (lock-containment "
                "inference: registry version-table / fleet pick-lock "
                "bug class)"))


def _check_dml017(flows: list, always: dict, rel: str,
                  findings: list) -> None:
    """Declared lock containment for the tenancy scheduler's state
    (ISSUE 18): any mutation of a _TENANCY_STATE_ATTRS field in serve/
    whose effective lock set is EMPTY is a finding — no two-site
    inference threshold like DML010, because this state's guard is a
    design contract (serve/tenancy.py's module docstring), not a
    pattern to be learned. __init__/__post_init__ construction is
    pre-publication and exempt."""
    for f in flows:
        if f.name.split(".")[-1] in ("__init__", "__post_init__"):
            continue
        base = always[f.name]
        for attr, lineno, held, desc, _is_self in f.mutations:
            if attr not in _TENANCY_STATE_ATTRS:
                continue
            if not (held | base):
                findings.append(Finding(
                    rel, lineno, "DML017",
                    f"mutation `{desc}` of declared-guarded tenancy "
                    "state outside any named lock — every admission/"
                    "fairness field mutates only under the "
                    "scheduler's condition (tenancy.sched), or a "
                    "grant decision can be torn mid-flight"))


# DML018: the only function names allowed to assign `*._cluster_epoch`
# (ISSUE 19). Construction is pre-publication; promote_fanout is the
# gateway's two-phase flip; apply_cluster_epoch is the worker-side
# /cluster/epoch receiving end. Everything else is a second epoch
# writer outside the barrier.
_CLUSTER_EPOCH_WRITERS = frozenset(
    ("__init__", "__post_init__", "promote_fanout",
     "apply_cluster_epoch"))


def _check_dml018(tree: ast.AST, rel: str, findings: list) -> None:
    """The cluster epoch mutates ONLY through the promote fan-out path
    (ISSUE 19): any assignment to a `_cluster_epoch` attribute whose
    enclosing function is not an allowed writer — or that sits at
    module level — is a finding. A simple enclosing-name check, not a
    dataflow pass: the contract is about WHICH code path may move the
    epoch, not about which lock it holds while doing so (DML010/017
    cover locking)."""

    def visit(node: ast.AST, func: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                visit(child, child.name)
                continue
            if isinstance(child, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and t.attr == "_cluster_epoch"
                            and func not in _CLUSTER_EPOCH_WRITERS):
                        where = (f"function {func!r}" if func
                                 else "module level")
                        findings.append(Finding(
                            rel, child.lineno, "DML018",
                            "cluster epoch assigned at "
                            f"{where} — the epoch moves only "
                            "through the promote fan-out "
                            "(Gateway.promote_fanout) or the worker "
                            "receiving end (apply_cluster_epoch); "
                            "any other writer bypasses the two-phase "
                            "barrier and re-opens the mixed-version "
                            "window"))
            visit(child, func)

    visit(tree, "")


# DML019: the capacity-actuation method names (ISSUE 20) and the only
# function name allowed to call them. scale_to is both actuators'
# single entry point; everything else calling an actuation method is a
# second scaler racing the control loop.
_ACTUATION_CALLS = frozenset(
    ("apply_scale", "add_worker", "drain_worker"))
_ACTUATION_CALLERS = frozenset(("scale_to",))


def _check_dml019(tree: ast.AST, rel: str, findings: list) -> None:
    """Capacity actuation flows ONLY through the Autoscaler's actuator
    path (ISSUE 20): any call to an actuation method (`apply_scale`,
    `add_worker`, `drain_worker` as attribute calls) whose innermost
    enclosing function is not `scale_to` — or that sits at module
    level — is a finding. Same enclosing-name discipline as DML018:
    the contract is about WHICH code path may move capacity, not how
    it locks while doing so."""

    def visit(node: ast.AST, func: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                visit(child, child.name)
                continue
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _ACTUATION_CALLS
                    and func not in _ACTUATION_CALLERS):
                where = (f"function {func!r}" if func
                         else "module level")
                findings.append(Finding(
                    rel, child.lineno, "DML019",
                    f"actuation call {child.func.attr!r} at {where} "
                    "— capacity moves only through an Actuator's "
                    "scale_to (serve/autoscale.py); a second caller "
                    "races the control loop's decisions and un-"
                    "prices its chip-second accounting"))
            visit(child, func)

    visit(tree, "")


def _check_dml011(tree: ast.AST, rel: str, findings: list) -> None:
    defs = {n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    jitted_statics: dict = {}     # bound name -> static param names

    def _static_sets(call: ast.Call):
        by_name: list = []
        by_num: list = []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                by_name = [c.value for c in ast.walk(kw.value)
                           if isinstance(c, ast.Constant)
                           and isinstance(c.value, str)]
            elif kw.arg == "static_argnums":
                by_num = [c.value for c in ast.walk(kw.value)
                          if isinstance(c, ast.Constant)
                          and isinstance(c.value, int)]
        return by_name, by_num

    for node in ast.walk(tree):
        # (a) the thread-local device pin, both spellings
        if (isinstance(node, ast.Attribute)
                and node.attr == "default_device"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"):
            findings.append(Finding(
                rel, node.lineno, "DML011",
                "jax.default_device is thread-local AND part of the "
                "jit cache key: programs warmed on this thread stay "
                "cold on every other worker thread (steady-state "
                "recompiles — the dryrun serve-reload trap); place "
                "arrays with explicit shardings/device_put instead"))
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "config"
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "jax"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_default_device"):
            findings.append(Finding(
                rel, node.lineno, "DML011",
                "jax.config.update('jax_default_device', ...) pins the "
                "thread-local default device into the jit cache key — "
                "the same cold-worker-thread recompile hazard as "
                "jax.default_device"))
        # (b) non-hashable static args on jax.jit
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "jit"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jax"):
            continue
        by_name, by_num = _static_sets(node)
        if not by_name and not by_num:
            continue
        tgt = node.args[0] if node.args else None
        fdef = defs.get(tgt.id) if isinstance(tgt, ast.Name) else None
        static_params = set(by_name)
        if fdef is not None:
            params = list(fdef.args.posonlyargs) + list(fdef.args.args)
            static_params |= {params[i].arg for i in by_num
                              if 0 <= i < len(params)}
            defaults = fdef.args.defaults
            offset = len(params) - len(defaults)
            for i, p in enumerate(params):
                if p.arg not in static_params or i < offset:
                    continue
                d = defaults[i - offset]
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    findings.append(Finding(
                        rel, node.lineno, "DML011",
                        f"static arg {p.arg!r} of jitted "
                        f"{fdef.name}() defaults to a non-hashable "
                        "mutable literal — the jit cache key cannot "
                        "hash it (TypeError on the first defaulted "
                        "call); use a tuple/frozen value"))
    # (b continued) call sites of locally-jitted names passing mutable
    # literals in static keyword positions
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "jit"
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id == "jax"):
            by_name, _ = _static_sets(node.value)
            if by_name:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_statics[t.id] = set(by_name)
    if jitted_statics:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jitted_statics):
                statics = jitted_statics[node.func.id]
                for kw in node.keywords:
                    if (kw.arg in statics
                            and isinstance(kw.value,
                                           (ast.List, ast.Dict,
                                            ast.Set))):
                        findings.append(Finding(
                            rel, node.lineno, "DML011",
                            f"non-hashable literal passed for static "
                            f"arg {kw.arg!r} of jitted "
                            f"{node.func.id}() — TypeError at the "
                            "call; pass a tuple/frozen value"))


def _check_dml012(tree: ast.AST, rel: str, findings: list) -> None:
    """Implicit host->device conversions in serve/ outside the engine
    staging path: jnp.array/jnp.asarray (host data -> device array on
    the spot) and jax.device_put (device placement belongs to the
    engine's staging discipline). np.asarray is host-side and free."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)):
            continue
        base, attr = node.func.value.id, node.func.attr
        if (base == "jnp" and attr in ("array", "asarray")) \
                or (base == "jax" and attr == "device_put"):
            findings.append(Finding(
                rel, node.lineno, "DML012",
                f"{base}.{attr}() in serve/ outside engine.py/"
                "quantize.py — an implicit host->device transfer "
                "bypassing the engine's pooled staging + device_put "
                "path (allowlist build/load-time placements with a "
                "reason)"))


# DML015: the dispatch-plumbing modules where engine dispatch/infer
# calls are the mechanism itself, not a bypass. Everything else in
# serve/ must go through the batcher's lane decision.
_DISPATCH_PLUMBING = frozenset(
    ("batcher.py", "engine.py", "router.py", "fleet.py"))


def _dml015_scope(rel: str) -> bool:
    return (_in_serve_pkg(rel)
            and os.path.basename(rel) not in _DISPATCH_PLUMBING)


def _check_dml015(tree: ast.AST, rel: str, findings: list) -> None:
    """Direct engine dispatch surface calls outside the dispatch
    plumbing (ISSUE 14): `.dispatch()`, `.dispatch_fast()` and
    `.infer()` attribute calls. The method names are specific enough
    that any hit in a non-plumbing serve/ module is a request path
    skipping the lane decision — or an admin-path measurement that
    must say so via the allowlist pragma."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("dispatch", "dispatch_fast",
                                       "infer")):
            findings.append(Finding(
                rel, node.lineno, "DML015",
                f".{node.func.attr}() called outside the dispatch "
                "plumbing (batcher/router/fleet/engine) — all "
                "dispatches must pass the lane decision so metrics/"
                "trace/faults are never skipped; allowlist admin-path "
                "measurements with a reason"))


def _dml016_scope(rel: str) -> bool:
    # cascade.py IS the confidence policy: it owns the margin math,
    # the calibration search and the one threshold accessor.
    return ((_in_serve_pkg(rel) or rel == "serve.py")
            and os.path.basename(rel) != "cascade.py")


def _check_dml016(tree: ast.AST, rel: str, findings: list) -> None:
    """Confidence-policy forks outside cascade.py (ISSUE 17): a
    softmax_margin() call — a per-row confidence read — or a margin-
    named value compared against a numeric literal. The calibrated
    threshold has exactly one accessor (cascade.threshold_of); a
    hardcoded confidence bar anywhere else routes traffic by a policy
    the composed-accuracy gate never judged."""

    def _margin_named(node) -> bool:
        if isinstance(node, ast.Name):
            return "margin" in node.id.lower()
        if isinstance(node, ast.Attribute):
            return "margin" in node.attr.lower()
        return False

    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and ((isinstance(node.func, ast.Name)
                      and node.func.id == "softmax_margin")
                     or (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "softmax_margin"))):
            findings.append(Finding(
                rel, node.lineno, "DML016",
                "softmax_margin() read outside cascade.py — per-row "
                "confidence decisions belong to the cascade front, "
                "gated by the one calibrated threshold "
                "(cascade.threshold_of)"))
        elif isinstance(node, ast.Compare):
            operands = [node.left, *node.comparators]
            if (any(_margin_named(n) for n in operands)
                    and any(isinstance(n, ast.Constant)
                            and isinstance(n.value, (int, float))
                            and not isinstance(n.value, bool)
                            for n in operands)):
                findings.append(Finding(
                    rel, node.lineno, "DML016",
                    "margin compared against a hardcoded numeric "
                    "constant — route the decision through the "
                    "calibrated threshold accessor "
                    "(cascade.threshold_of); a literal confidence bar "
                    "is a policy fork no composed-accuracy gate "
                    "judged"))


def _check_dml013(tree: ast.AST, rel: str, findings: list) -> None:
    """Bare numeric literals reaching jitted call sites as traced
    (non-static) arguments — the weak-type cache-key split. Covers
    names bound from jax.jit (`f = jax.jit(...)`; `self._forward =
    jax.jit(...)`) and their local call sites."""

    def _jit_call(value) -> bool:
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "jit"
                and isinstance(value.func.value, ast.Name)
                and value.func.value.id == "jax")

    def _statics(call: ast.Call) -> tuple:
        by_name: set = set()
        by_num: set = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                by_name = {c.value for c in ast.walk(kw.value)
                           if isinstance(c, ast.Constant)
                           and isinstance(c.value, str)}
            elif kw.arg == "static_argnums":
                by_num = {c.value for c in ast.walk(kw.value)
                          if isinstance(c, ast.Constant)
                          and isinstance(c.value, int)}
        return by_name, by_num

    def _params(fn_node) -> Optional[list]:
        """Positional parameter names of a wrapped def/lambda, or None
        when the wrapped object's signature is not locally visible."""
        if fn_node is None:
            return None
        a = fn_node.args
        return [p.arg for p in (a.posonlyargs + a.args)]

    # Local defs (module- and class-level) by name, for resolving
    # static_argnames back to positions at positional call sites.
    defs: dict = {n.name: n for n in ast.walk(tree)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))}

    jitted: dict = {}     # bound name/attr -> (by_name, by_num, params)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _jit_call(node.value):
            by_name, by_num = _statics(node.value)
            wrapped = node.value.args[0] if node.value.args else None
            if isinstance(wrapped, ast.Lambda):
                params = _params(wrapped)
            elif isinstance(wrapped, ast.Name):
                params = _params(defs.get(wrapped.id))
            else:
                params = None
            statics = (by_name, by_num, params)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jitted[t.id] = statics
                elif isinstance(t, ast.Attribute):
                    jitted[t.attr] = statics
    if not jitted:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Name) and func.id in jitted:
            name = func.id
        elif isinstance(func, ast.Attribute) and func.attr in jitted:
            name = func.attr
        if name is None:
            continue
        by_name, by_num, params = jitted[name]
        for i, arg in enumerate(node.args):
            if i in by_num:
                continue        # static: hashed, not traced
            if params is not None and i < len(params) \
                    and params[i] in by_name:
                continue        # static by NAME at a positional site —
                #   jax resolves static_argnames via the signature
            if params is None and by_name:
                continue        # signature not visible: a positional
                #   arg MAY be a static_argnames param — stay quiet
                #   rather than fail the gate on correct code
            if (isinstance(arg, ast.Constant)
                    and type(arg.value) in (int, float)):
                findings.append(Finding(
                    rel, node.lineno, "DML013",
                    f"bare {type(arg.value).__name__} literal "
                    f"{arg.value!r} passed to jitted {name}() traces "
                    "weak-typed — a second cache entry vs the "
                    "committed-array spelling of the same call; pass "
                    "an array/np scalar or make the arg static"))
        for kw in node.keywords:
            if kw.arg in by_name or kw.arg is None:
                continue
            if (isinstance(kw.value, ast.Constant)
                    and type(kw.value.value) in (int, float)):
                findings.append(Finding(
                    rel, node.lineno, "DML013",
                    f"bare {type(kw.value.value).__name__} literal "
                    f"{kw.value.value!r} passed to jitted {name}() "
                    f"as {kw.arg}= traces weak-typed — a second cache "
                    "entry vs the committed-array spelling; pass an "
                    "array/np scalar or make the arg static"))


_FAULTS_REL = "distributedmnist_tpu/serve/faults.py"
_LINT_SELFTEST_REL = "tests/test_analysis_lint.py"


def check_failpoint_coverage(texts: dict) -> list:
    """DML014, the project-level cross-check: every name declared in
    faults.KNOWN_FAILPOINTS must be EXERCISED — referenced by a test
    (exact-name string constant or spec string in tests/) or named in
    a spec-shaped chaos-schedule string anywhere in the repo (the
    bench's programmatic schedules count; f-string fragments are
    scanned piece by piece). `texts` maps repo-relative posix paths to
    file contents; findings anchor at the declaration line in
    faults.py."""
    faults_text = texts.get(_FAULTS_REL)
    if faults_text is None:
        return []
    try:
        faults_tree = ast.parse(faults_text)
    except SyntaxError:
        return []               # DML000 already reported by lint_source
    declared: list = []         # (name, lineno), declaration order
    for node in ast.walk(faults_tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "KNOWN_FAILPOINTS"
                        for t in node.targets)):
            for c in ast.walk(node.value):
                if (isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                        and _FAILPOINT_NAME_RE.match(c.value)):
                    declared.append((c.value, c.lineno))
    if not declared:
        return []
    known = {n for n, _ in declared}
    exercised: set = set()
    for rel, text in texts.items():
        if rel in (_FAULTS_REL, _LINT_SELFTEST_REL):
            # the weave/declaration is not coverage — and neither are
            # the lint suite's OWN fixtures, which must spell real
            # failpoint names to keep DML003 quiet: counting them
            # would mask DML014 for exactly those names forever
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        in_tests = rel.startswith("tests/")
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            s = node.value.strip()
            if in_tests and s in known:
                exercised.add(s)
            if _SPEC_SHAPED_RE.match(s):
                exercised.update(n for n in _spec_segment_names(s)
                                 if n in known)
    findings = []
    for name, lineno in declared:
        if name not in exercised:
            findings.append(Finding(
                _FAULTS_REL, lineno, "DML014",
                f"failpoint {name!r} is declared in KNOWN_FAILPOINTS "
                "but exercised by no test and named in no chaos spec "
                "— its failure path is untested (add a test/spec, or "
                "remove the stale weave)"))
    return findings


def _dml009_scope(rel: str) -> bool:
    return _primitive_scope(rel)


def _dml010_scope(rel: str) -> bool:
    return _in_serve_pkg(rel)


def _dml011_scope(rel: str) -> bool:
    return _thread_scope(rel)


def _dml017_scope(rel: str) -> bool:
    return _in_serve_pkg(rel)


def _dml018_scope(rel: str) -> bool:
    return _in_serve_pkg(rel) or rel == "serve.py"


def _dml019_scope(rel: str) -> bool:
    return _in_serve_pkg(rel) or rel == "serve.py"


def _dml012_scope(rel: str) -> bool:
    # engine.py IS the staging path; quantize.py is build-time weight
    # preparation the engine device_puts as a whole.
    return (_in_serve_pkg(rel)
            and os.path.basename(rel) not in ("engine.py",
                                              "quantize.py"))


def _dml013_scope(rel: str) -> bool:
    return _thread_scope(rel)


# -- the checker -----------------------------------------------------------

def lint_source(text: str, rel: str) -> list:
    """All findings for one file's source. `rel` is the repo-relative
    posix path (it decides which rules apply). Pragma suppression is
    applied by the caller via apply_allowlist (kept separate so tests
    can assert raw findings)."""
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 0, "DML000",
                        f"file does not parse: {e.msg}")]
    findings: list = []
    docstrings = _docstring_nodes(tree)
    known = _known_failpoints() if _failpoint_scope(rel) else frozenset()
    # String constants already checked as failpoint/parse_spec call
    # arguments — the generic spec-shaped scan skips them (ast.walk is
    # breadth-first, so a Call is always visited before its children).
    spec_arg_ids: set = set()

    # finally-containment index for DML006: every node id located under
    # some Try's finalbody.
    in_finally: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    in_finally.add(id(sub))

    # lock-containment index for DML008: every node id located inside a
    # `with <...>_lock:` block (any expression whose trailing name ends
    # in `_lock` counts — `self._lock`, `cache._lock`, a bare `_lock`).
    def _is_lock_expr(e: ast.AST) -> bool:
        if isinstance(e, ast.Attribute):
            return e.attr.endswith("_lock")
        if isinstance(e, ast.Name):
            return e.id.endswith("_lock")
        return False

    under_lock: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.With) and any(
                _is_lock_expr(item.context_expr) for item in node.items):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    under_lock.add(id(sub))

    def _cache_state_attr(expr: ast.AST) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and expr.attr in _CACHE_STATE_ATTRS):
            return expr.attr
        return None

    for node in ast.walk(tree):
        # DML001 / DML002: bare threading constructors.
        if isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "threading"):
                if func.attr in _BARE_PRIMITIVES and _primitive_scope(rel):
                    findings.append(Finding(
                        rel, node.lineno, "DML001",
                        f"bare threading.{func.attr}() — use "
                        "analysis.locks.make_"
                        f"{func.attr.lower().replace('bounded', '')}"
                        "(name) so the sanitizer can track it"))
                elif func.attr == "Thread" and _thread_scope(rel):
                    findings.append(Finding(
                        rel, node.lineno, "DML002",
                        "bare threading.Thread() — use "
                        "analysis.locks.make_thread(target, name, "
                        "daemon) (explicit daemon decision, sanitizer-"
                        "registered)"))
            # DML004: time.time() calls.
            if (_time_scope(rel) and isinstance(func, ast.Attribute)
                    and func.attr == "time"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "time"):
                findings.append(Finding(
                    rel, node.lineno, "DML004",
                    "time.time() — use time.monotonic()/perf_counter() "
                    "for any elapsed/latency/ordering math; allowlist "
                    "pure wall-clock display stamps with a reason"))
            # DML005: jax.jit outside engine/quantize.
            if (_jit_scope(rel) and isinstance(func, ast.Attribute)
                    and func.attr == "jit"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "jax"):
                findings.append(Finding(
                    rel, node.lineno, "DML005",
                    "jax.jit outside serve/engine.py|quantize.py — "
                    "compiled serving programs are built only in the "
                    "engine/warmup construction path (steady-state "
                    "recompile hazard)"))
            # DML003 (call form): failpoint("name", ...) and
            # parse_spec/from_spec("spec...").
            cname = _call_name(func)
            if known and cname == "failpoint" and node.args:
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    spec_arg_ids.add(id(arg))
                    if arg.value not in known:
                        findings.append(Finding(
                            rel, node.lineno, "DML003",
                            f"failpoint name {arg.value!r} is not in "
                            "faults.KNOWN_FAILPOINTS — it would never "
                            "fire"))
            if known and cname in ("parse_spec", "from_spec") and node.args:
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    spec_arg_ids.add(id(arg))
                    for name in _spec_segment_names(arg.value):
                        if name not in known:
                            findings.append(Finding(
                                rel, node.lineno, "DML003",
                                f"fault spec names unknown failpoint "
                                f"{name!r} (known: would be rejected "
                                "at install — fix the schedule)"))
            # DML006: staging-pool recycle outside finally.
            if (_in_serve_pkg(rel) and isinstance(func, ast.Attribute)
                    and func.attr == "append"):
                recv = func.value
                if (isinstance(recv, ast.Subscript)
                        and isinstance(recv.value, ast.Attribute)
                        and recv.value.attr == "_staging_pool"
                        and id(node) not in in_finally):
                    findings.append(Finding(
                        rel, node.lineno, "DML006",
                        "staging-pool recycle outside a finally block — "
                        "an error path here leaks one pooled buffer per "
                        "failure (the PR 5 fetch-storm leak)"))
        # DML008: cache-state mutation outside the cache's named lock
        # (ISSUE 10). Three mutation shapes: a mutating method call on
        # _entries/_flights, a subscript store into one, a subscript
        # delete from one. Reads (.get/.items/len) and whole-attribute
        # rebinding in a constructor are free.
        if _in_serve_pkg(rel):
            hit = None
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _MUTATING_METHODS
                        and _cache_state_attr(f.value)):
                    hit = (node, f"{_cache_state_attr(f.value)}"
                                 f".{f.attr}()")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and _cache_state_attr(t.value)):
                        hit = (node, f"{_cache_state_attr(t.value)}"
                                     "[...] = ...")
                        break
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and _cache_state_attr(t.value)):
                        hit = (node, "del "
                                     f"{_cache_state_attr(t.value)}"
                                     "[...]")
                        break
            if hit is not None and id(hit[0]) not in under_lock:
                findings.append(Finding(
                    rel, hit[0].lineno, "DML008",
                    f"cache state mutation {hit[1]} outside a "
                    "`with <cache>._lock:` block — concurrent "
                    "lookups, the single-flight done-callback and the "
                    "registry's invalidation hook race this state "
                    "(torn LRU / double-resolved follower)"))
        # DML003 (literal form): spec-shaped string constants anywhere
        # outside docstrings — catches the bench's concatenated /
        # f-string chaos schedules piece by piece.
        if (known and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in docstrings
                and id(node) not in spec_arg_ids
                and _SPEC_SHAPED_RE.match(node.value.strip())):
            for name in _spec_segment_names(node.value):
                if name not in known:
                    findings.append(Finding(
                        rel, node.lineno, "DML003",
                        f"spec-shaped literal names unknown failpoint "
                        f"{name!r} — a schedule built from it would "
                        "inject nothing"))

    # DML007: a begin_span() whose statement is not immediately
    # followed by a try with an end_span() in a finally. Statement
    # lists are scanned structurally (function bodies, if/for/with
    # bodies, except handlers), so a begin at any nesting depth is
    # checked against ITS OWN statement list. Only simple statements
    # can carry the call (they hold no nested statement lists), so
    # nothing is double-reported.
    if _span_scope(rel):
        simple = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                  ast.Return, ast.Raise)
        for node in ast.walk(tree):
            bodies = [getattr(node, f) for f in ("body", "orelse",
                                                 "finalbody")
                      if isinstance(getattr(node, f, None), list)]
            for stmts in bodies:
                for i, stmt in enumerate(stmts):
                    if not isinstance(stmt, simple):
                        continue
                    begins = [sub for sub in ast.walk(stmt)
                              if isinstance(sub, ast.Call)
                              and _call_name(sub.func) == "begin_span"]
                    if not begins:
                        continue
                    nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                    protected = (
                        isinstance(stmt, ast.Assign)
                        and isinstance(nxt, ast.Try) and any(
                            isinstance(sub, ast.Call)
                            and _call_name(sub.func) == "end_span"
                            and id(sub) in in_finally
                            for sub in ast.walk(nxt)))
                    if not protected:
                        findings.append(Finding(
                            rel, begins[0].lineno, "DML007",
                            "begin_span() without an immediate "
                            "try/finally end_span — an exception "
                            "mid-stage would leave the span open and "
                            "skew every attribution derived from it "
                            "(spans ending on another thread use "
                            "add_span with measured endpoints "
                            "instead)"))

    # DML009/DML010: the interprocedural dataflow pass (shared lock
    # vocabulary + always-held inference, computed once per module).
    if (_dml009_scope(rel) or _dml010_scope(rel)
            or _dml017_scope(rel)):
        lock_names = _lock_attr_names(tree)
        flows = _collect_flows(tree, lock_names)
        always = _always_held(flows)
        if _dml009_scope(rel):
            _check_dml009(flows, always, rel, findings)
        if _dml010_scope(rel):
            _check_dml010(flows, always, rel, findings)
        # DML017: declared lock containment for the tenancy
        # scheduler's state (ISSUE 18) — same flows/always pass.
        if _dml017_scope(rel):
            _check_dml017(flows, always, rel, findings)
    # DML011: jit-cache-key hazards in serving/bench code.
    if _dml011_scope(rel):
        _check_dml011(tree, rel, findings)
    # DML012/DML013: the compile-surface siblings (ISSUE 12) — implicit
    # host->device conversions off the staging path, weak-type literals
    # at jitted call sites. DML014 is project-level (lint_paths).
    if _dml012_scope(rel):
        _check_dml012(tree, rel, findings)
    if _dml013_scope(rel):
        _check_dml013(tree, rel, findings)
    # DML015: dispatches outside the lane-deciding plumbing (ISSUE 14).
    if _dml015_scope(rel):
        _check_dml015(tree, rel, findings)
    # DML016: confidence-policy forks outside the cascade's calibrated
    # threshold (ISSUE 17).
    if _dml016_scope(rel):
        _check_dml016(tree, rel, findings)
    # DML018: cluster-epoch writes outside the promote fan-out path
    # (ISSUE 19).
    if _dml018_scope(rel):
        _check_dml018(tree, rel, findings)
    # DML019: capacity actuation outside the Autoscaler's actuator
    # path (ISSUE 20).
    if _dml019_scope(rel):
        _check_dml019(tree, rel, findings)
    return findings


def apply_allowlist(findings: list, lines: list) -> tuple:
    """Split findings into (active, allowed) per the pragma on the
    finding's line or the line above. A pragma without a reason does
    NOT suppress."""
    active, allowed = [], []
    for f in findings:
        reason = None
        for ln in (f.line, f.line - 1):
            if 1 <= ln <= len(lines):
                m = _PRAGMA_RE.search(lines[ln - 1])
                if m and m.group(1) == f.rule and m.group(2):
                    reason = m.group(2).strip()
                    break
        if reason is not None:
            f.allowed = True
            f.allow_reason = reason
            allowed.append(f)
        else:
            active.append(f)
    return active, allowed


def iter_python_files(root: str) -> Iterable[tuple]:
    """(abs_path, rel_posix) for every lintable .py under the repo:
    the package, tests, scripts, and the top-level entry points."""
    skip_dirs = {"__pycache__", ".git", ".claude"}
    for base in ("distributedmnist_tpu", "tests", "scripts"):
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, root).replace(os.sep, "/")
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            yield os.path.join(root, fn), fn


def lint_paths(root: str) -> tuple:
    active: list = []
    allowed: list = []
    texts: dict = {}
    for path, rel in iter_python_files(root):
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
        texts[rel] = text
        a, ok = apply_allowlist(lint_source(text, rel),
                                text.splitlines())
        active.extend(a)
        allowed.extend(ok)
    # DML014 needs the WHOLE repo's texts (a failpoint is covered by a
    # test or spec in some OTHER file) — run it once, after the
    # per-file pass, and put its findings through the same allowlist
    # against faults.py's own lines.
    d14 = check_failpoint_coverage(texts)
    if d14:
        a, ok = apply_allowlist(
            d14, texts.get(_FAULTS_REL, "").splitlines())
        active.extend(a)
        allowed.extend(ok)
    return active, allowed


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedmnist_tpu.analysis",
        description="Project lint: serving-stack concurrency/correctness "
                    "rules codified from past review findings. Exit 0 "
                    "clean, 1 on findings, 2 on internal error.")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--show-allowed", action="store_true",
                   help="also print pragma-allowlisted findings")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, (summary, why) in sorted(RULES.items()):
            print(f"{rule}  {summary}\n        {why}")
        return 0

    root = os.path.abspath(args.root) if args.root else repo_root()
    try:
        active, allowed = lint_paths(root)
    except Exception as e:           # broken lint must not read as clean
        print(f"lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    for f in sorted(active, key=lambda f: (f.path, f.line, f.rule)):
        print(f.format())
    if args.show_allowed:
        for f in sorted(allowed, key=lambda f: (f.path, f.line, f.rule)):
            print(f"ALLOWED {f.format()}  [{f.allow_reason}]")
    print(f"lint: {len(active)} finding(s), {len(allowed)} allowlisted",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
