"""ANALYSIS_r*.json artifact emission (ISSUE 11): the analysis
trajectory, numbered like the BENCH rounds.

Perf has BENCH_serve_r*.json; analysis coverage gets the same
treatment — every explorer sweep (scripts/explore.sh, or
`python -m distributedmnist_tpu.analysis.explore --emit`) and every
opted-in sanitizer verdict (`Sanitizer.assert_clean(artifact=...)`, or
DMNIST_ANALYSIS_ARTIFACT=1) writes a machine-readable round record:
findings, schedules explored, seeds, wall time. Round numbers are
allocated by scanning the repo root for existing ANALYSIS_r*.json —
append-only history, never overwritten."""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

_ROUND_RE = re.compile(r"^ANALYSIS_r(\d+)\.json$")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def existing_rounds(root: Optional[str] = None) -> list:
    root = root or repo_root()
    out = []
    for fn in os.listdir(root):
        m = _ROUND_RE.match(fn)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def next_round(root: Optional[str] = None) -> int:
    rounds = existing_rounds(root)
    return (rounds[-1] + 1) if rounds else 1


def emit_analysis(payload: dict, root: Optional[str] = None,
                  round: Optional[int] = None) -> str:
    """Write one ANALYSIS_rNN.json round record and return its path.
    The payload is annotated with the round number and a wall-clock
    display stamp (provenance only — nothing orders by it)."""
    root = root or repo_root()
    record = dict(payload)
    record.setdefault(
        "generated_at",
        time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    while True:
        rnd = round if round is not None else next_round(root)
        path = os.path.join(root, f"ANALYSIS_r{rnd:02d}.json")
        try:
            fh = open(path, "x", encoding="utf-8")
        except FileExistsError:
            if round is not None:
                raise
            continue  # concurrent emitter took this round; re-scan
        with fh:
            record["round"] = rnd
            json.dump(record, fh, indent=2, sort_keys=False)
            fh.write("\n")
        return path
