"""Deterministic concurrency model checker over the named-primitive
substrate (ISSUE 11): loom/CHESS-style schedule exploration.

The PR 8 sanitizer OBSERVES whatever schedules happen to run; every
hard serving bug so far (fleet staging leak, mid-promote
misattribution, shadow FIFO inflation, follower-skip races) was an
*interleaving* bug ordinary tests catch only by luck. This module
closes the gap: the same `analysis/locks.py` factories that name every
serve primitive become the instrumentation points of a **controller**
that runs threads one-at-a-time — every acquire/release/wait/notify/
semaphore/FIFO op is a yield point, the controller picks which thread
advances next, and the whole interleaving is a *replayable seed*
instead of a flake.

Mechanics (Controller):

- Threads built through `make_thread` (and `Controller.spawn`) are real
  OS threads gated per-step by an Event: exactly one runs at a time,
  everyone else is parked at a yield point with a *ready predicate*
  (lock free, semaphore > 0, FIFO non-empty, condition notified,
  future done, thread finished). The scheduler loop computes the
  enabled set, asks the policy for a choice, wakes it, and waits for it
  to park again — so shadow primitive state is only ever mutated by the
  single running thread and the enabled set is evaluated at quiescence.
- Primitives built through `make_lock`/`make_rlock`/`make_condition`/
  `make_semaphore`/`make_fifo` under an installed controller are pure
  Python state machines (no real locking needed — one thread runs at a
  time). Condition `wait(timeout)` models the timeout as "eligible to
  wake at any schedule step" (spurious wakeup / expiry); untimed
  `wait()` wakes only on notify — which is how lost-wakeup bugs become
  *reachable deadlocks* instead of 0.1 s stalls.
- `time.monotonic`/`time.perf_counter` are patched to a **logical
  clock** that ticks once per scheduled step (and fast-forwards through
  `time.sleep`), so coalesce windows and deadline math are
  deterministic functions of the schedule, not the host.
- An empty enabled set with live threads is reported as a **deadlock**
  (each thread's pending op and target named); a thread blocking on an
  uninstrumented primitive trips a real-time watchdog and is reported
  as such — never a silent hang.

Schedules (Explorer):

- `RandomPolicy(seed)`: uniform choice among enabled threads — the
  workhorse. One seed = one schedule; replaying the seed replays the
  identical interleaving and the identical finding (asserted by the
  replay-determinism test).
- `DfsPolicy`: bounded systematic DFS over choice points with a
  partial-order reduction on independent primitive *names* — when
  every enabled thread's pending op targets a distinct primitive the
  ops commute at the protocol level, so the step is executed without
  branching; only conflicting steps (two threads about to touch the
  same name) become DFS choice points. (Plain-field data races between
  yield points are outside this model — the lint's DML010 containment
  inference covers those statically.)

Findings carry (machine, seed, step, detail, schedule trace); the
harnesses in `analysis/harnesses.py` assert each machine's invariants
across N explored schedules, and a planted-mutation self-test proves
the explorer actually finds the bug classes it exists for.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import threading
import time
import traceback
from collections import Counter, deque
from typing import Callable, Optional

# Real clocks, captured before any patching: the controller's own
# machinery (watchdog, wall timing) must never read its logical clock.
_REAL_MONOTONIC = time.monotonic
_REAL_PERF = time.perf_counter
_REAL_SLEEP = time.sleep


class Killed(BaseException):
    """Raised at yield points of still-parked threads when a run is
    aborted (finding recorded, budget exhausted): unwinds the thread
    through its finallys and out. BaseException so serve code's
    `except Exception` failure paths cannot swallow it."""


class InvariantViolation(AssertionError):
    """A machine invariant failed under some schedule."""


# The module-global active controller (None in production and in every
# non-exploring test — the locks.py factories check it first).
_active: Optional["Controller"] = None


def active_controller() -> Optional["Controller"]:
    return _active


def _ctl_monotonic() -> float:
    c = _active
    return _REAL_MONOTONIC() if c is None else c.base + c.clock


def _ctl_perf_counter() -> float:
    c = _active
    return _REAL_PERF() if c is None else c.base + c.clock


def _ctl_sleep(seconds) -> None:
    c = _active
    if c is None:
        return _REAL_SLEEP(seconds)
    c.on_sleep(float(seconds))


# -- tasks -----------------------------------------------------------------


class _Task:
    """One controlled thread's scheduling state."""

    __slots__ = ("tid", "name", "thread", "state", "gate", "pending",
                 "ready", "exc", "daemon")

    def __init__(self, tid: int, name: str, thread, daemon: bool):
        self.tid = tid
        self.name = name
        self.thread = thread
        self.daemon = daemon
        # "parked"   — at a yield point, waiting for a grant
        # "running"  — granted, executing until its next yield/finish
        # "finished" — run() returned (or unwound)
        self.state = "parked"
        self.gate = threading.Event()
        self.pending = ("thread.start", name)
        self.ready: Optional[Callable[[], bool]] = None
        self.exc: Optional[BaseException] = None

    def is_ready(self) -> bool:
        if self.ready is None:
            return True
        return bool(self.ready())


class _ControlledThread(threading.Thread):
    """make_thread's product under an installed controller: a real
    thread whose body is gated by the scheduler. join() is cooperative
    (a yield point blocking on the target's completion) — a thread that
    never finishes surfaces as a deadlock, not a silent timeout."""

    def __init__(self, ctl: "Controller", target, name: str,
                 daemon: bool, args: tuple, kwargs: dict):
        super().__init__(name=name, daemon=daemon)
        self._ctl = ctl
        self._body = (target, args, kwargs)
        self._task: Optional[_Task] = None

    def start(self) -> None:
        self._task = self._ctl._register(self.name, self, self.daemon)
        super().start()

    def run(self) -> None:
        target, args, kwargs = self._body
        self._ctl._run_task(self._task, target, args, kwargs)

    def join(self, timeout: Optional[float] = None) -> None:
        task = self._task
        if task is None:
            return
        if self._ctl.current_task() is not None:
            if timeout is None:
                self._ctl.yield_point(
                    "thread.join", task.name,
                    ready=lambda: task.state == "finished")
            else:
                # Timed join models production faithfully: "the
                # timeout may fire at any step", so schedules where
                # stop() abandons a still-running thread are explored
                # instead of mis-reported as deadlocks.
                self._ctl.yield_point("thread.join", task.name)
        if task.state == "finished":
            super().join(timeout=2.0)


# -- controlled primitives -------------------------------------------------


class _CtlLock:
    """Shadow mutex: ownership is plain state (only one thread runs at
    a time), acquisition is a yield point gated on availability."""

    def __init__(self, ctl: "Controller", name: str):
        self._ctl = ctl
        self.name = name
        self._owner: Optional[_Task] = None
        ctl._register_prim(name, self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ctl = self._ctl
        if blocking and (timeout is None or timeout < 0):
            ctl.yield_point("lock.acquire", self.name,
                            ready=lambda: self._owner is None)
        else:
            # non-blocking / timed: eligible any step; may fail
            ctl.yield_point("lock.tryacquire", self.name)
            if self._owner is not None:
                return False
        self._owner = ctl.current_task()
        return True

    def release(self) -> None:
        self._ctl.yield_point("lock.release", self.name)
        self._owner = None

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def _free(self) -> bool:
        return self._owner is None

    def __repr__(self) -> str:
        return f"<CtlLock {self.name!r} owner={getattr(self._owner, 'name', None)!r}>"


class _CtlRLock:
    def __init__(self, ctl: "Controller", name: str):
        self._ctl = ctl
        self.name = name
        self._owner: Optional[_Task] = None
        self._depth = 0
        ctl._register_prim(name, self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ctl = self._ctl
        me = ctl.current_task()
        ctl.yield_point(
            "rlock.acquire", self.name,
            ready=lambda: self._owner is None or self._owner is me)
        self._owner = me
        self._depth += 1
        return True

    def release(self) -> None:
        self._ctl.yield_point("rlock.release", self.name)
        self._depth -= 1
        if self._depth == 0:
            self._owner = None

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition-protocol helpers used by _CtlCondition (no yields of
    # their own — the condition's wait sequences the yields).
    def _release_all(self) -> int:
        depth = self._depth
        self._owner = None
        self._depth = 0
        return depth

    def _restore(self, task: _Task, depth: int) -> None:
        self._owner = task
        self._depth = depth

    def _free(self) -> bool:
        return self._owner is None

    def __repr__(self) -> str:
        return f"<CtlRLock {self.name!r} depth={self._depth}>"


class _CtlCondition:
    """Shadow condition variable over a _CtlRLock (the same reentrant
    semantics as production threading.Condition()). wait(timeout=None)
    wakes only on notify; a timed wait is additionally eligible to wake
    at any schedule step — the model of "the timeout may fire"."""

    def __init__(self, ctl: "Controller", name: str):
        self._ctl = ctl
        self.name = name
        self._lock = _CtlRLock(ctl, name)
        self._waiters: list = []      # [task, {"notified": bool}]

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        return self._lock.acquire()

    def __exit__(self, *exc) -> None:
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ctl = self._ctl
        me = ctl.current_task()
        if self._lock._owner is not me:
            raise RuntimeError("cannot wait on un-acquired condition "
                               f"{self.name!r}")
        token = {"notified": False}
        self._waiters.append((me, token))
        depth = self._lock._release_all()
        if timeout is None:
            ctl.yield_point("cond.wait", self.name,
                            ready=lambda: token["notified"])
        else:
            # timed wait: wake on notify OR at any step (expiry model);
            # fast-forward the logical clock so wait-until-deadline
            # loops converge
            ctl.advance_clock(min(max(timeout, 0.0), 0.05))
            ctl.yield_point("cond.timedwait", self.name)
        try:
            self._waiters.remove((me, token))
        except ValueError:
            pass
        ctl.yield_point("cond.reacquire", self.name,
                        ready=self._lock._free)
        self._lock._restore(me, depth)
        return token["notified"]

    def wait_for(self, predicate, timeout: Optional[float] = None):
        result = predicate()
        while not result:
            self.wait(timeout)
            result = predicate()
            if timeout is not None and not result:
                break
        return result

    def notify(self, n: int = 1) -> None:
        self._ctl.yield_point("cond.notify", self.name)
        # real Condition.notify removes waiters from its deque, so two
        # notify(1) calls wake two DISTINCT waiters even before either
        # gets scheduled — skip already-notified tokens to match
        remaining = n
        for _, token in self._waiters:
            if remaining <= 0:
                break
            if not token["notified"]:
                token["notified"] = True
                remaining -= 1

    def notify_all(self) -> None:
        self._ctl.yield_point("cond.notify", self.name)
        for _, token in self._waiters:
            token["notified"] = True

    def _free(self) -> bool:
        return self._lock._free()

    def __repr__(self) -> str:
        return f"<CtlCondition {self.name!r} waiters={len(self._waiters)}>"


class _CtlSemaphore:
    """Shadow counting semaphore; the controller keeps a per-name net
    acquire-release balance (the harnesses' window-balance-zero
    invariant, mirroring the sanitizer's resource accounting)."""

    def __init__(self, ctl: "Controller", name: str, value: int):
        self._ctl = ctl
        self.name = name
        self._value = value
        ctl._register_prim(name, self)

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        ctl = self._ctl
        if blocking and timeout is None:
            ctl.yield_point("sem.acquire", self.name,
                            ready=lambda: self._value > 0)
        else:
            ctl.yield_point("sem.tryacquire", self.name)
            if self._value <= 0:
                return False
        self._value -= 1
        ctl.sem_balance[self.name] = ctl.sem_balance.get(self.name, 0) + 1
        return True

    def release(self, n: int = 1) -> None:
        ctl = self._ctl
        ctl.yield_point("sem.release", self.name)
        self._value += n
        ctl.sem_balance[self.name] = ctl.sem_balance.get(self.name, 0) - n

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<CtlSemaphore {self.name!r} value={self._value}>"


class _CtlFifo:
    """Shadow SimpleQueue (make_fifo): put never blocks, get parks on
    non-empty — the batcher's dispatch->completion handle queue becomes
    explorable instead of an uninstrumented real block."""

    def __init__(self, ctl: "Controller", name: str):
        self._ctl = ctl
        self.name = name
        self._q: deque = deque()

    def put(self, item) -> None:
        self._ctl.yield_point("fifo.put", self.name)
        self._q.append(item)

    def get(self, block: bool = True,
            timeout: Optional[float] = None):
        ctl = self._ctl
        if block and timeout is None:
            ctl.yield_point("fifo.get", self.name,
                            ready=lambda: len(self._q) > 0)
        else:
            ctl.yield_point("fifo.tryget", self.name)
            if not self._q:
                import queue as _queue

                raise _queue.Empty
        return self._q.popleft()

    def empty(self) -> bool:
        return not self._q

    def qsize(self) -> int:
        return len(self._q)

    def __repr__(self) -> str:
        return f"<CtlFifo {self.name!r} depth={len(self._q)}>"


# -- the controller --------------------------------------------------------


class Controller:
    """One schedule's cooperative scheduler: owns the tasks, the shadow
    primitives, the logical clock, the schedule trace and the (single)
    finding. Use via Explorer, or directly:

        ctl = Controller(policy=RandomPolicy(seed))
        ctl.explore(machine)        # machine.run(ctl) builds + drives
        ctl.finding                 # None, or the recorded finding dict
    """

    def __init__(self, policy=None, max_steps: int = 20000,
                 tick_s: float = 0.0005, watchdog_s: float = 20.0):
        self.policy = policy if policy is not None else RandomPolicy(0)
        self.max_steps = max_steps
        self.tick_s = tick_s
        self.watchdog_s = watchdog_s
        self.base = 1000.0            # logical monotonic origin
        self.clock = 0.0
        self.steps = 0
        self.trace: list = []         # (step, thread, op, target)
        self.finding: Optional[dict] = None
        self.completed = False        # every task ran to completion
        self.pruned = False           # DFS sleep-set redundant prefix
        self.aborted = False
        self.sem_balance: dict[str, int] = {}
        self.tasks: list[_Task] = []
        self.prims: dict[str, list] = {}
        self._tls = threading.local()
        self._cv = threading.Condition()
        self._tid = 0
        self._names: Counter = Counter()
        self._patched = False

    # -- factory surface (locks.py delegates here) -------------------------

    def new_lock(self, name: str) -> _CtlLock:
        return _CtlLock(self, name)

    def new_rlock(self, name: str) -> _CtlRLock:
        return _CtlRLock(self, name)

    def new_condition(self, name: str) -> _CtlCondition:
        return _CtlCondition(self, name)

    def new_semaphore(self, name: str, value: int) -> _CtlSemaphore:
        return _CtlSemaphore(self, name, value)

    def new_fifo(self, name: str) -> _CtlFifo:
        return _CtlFifo(self, name)

    def new_thread(self, target, name: str, daemon: bool,
                   args: tuple = (), kwargs: Optional[dict] = None
                   ) -> _ControlledThread:
        return _ControlledThread(self, target, name, daemon, args,
                                 kwargs or {})

    def spawn(self, fn, name: str) -> _ControlledThread:
        """Harness helper: start a controlled daemon thread."""
        t = self.new_thread(fn, name=name, daemon=True)
        t.start()
        return t

    # -- task plumbing ------------------------------------------------------

    def current_task(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def _register(self, name: str, thread, daemon: bool) -> _Task:
        with self._cv:
            self._names[name] += 1
            if self._names[name] > 1:
                name = f"{name}#{self._names[name]}"
            self._tid += 1
            task = _Task(self._tid, name, thread, daemon)
            self.tasks.append(task)
            self._cv.notify_all()
        return task

    def _register_prim(self, name: str, prim) -> None:
        self.prims.setdefault(name, []).append(prim)

    def _run_task(self, task: _Task, target, args, kwargs) -> None:
        self._tls.task = task
        try:
            task.gate.wait()
            task.gate.clear()
            if not self.aborted:
                target(*args, **kwargs)
        except Killed:
            pass
        except BaseException as e:        # reported as a finding
            task.exc = e
        finally:
            with self._cv:
                task.state = "finished"
                self._cv.notify_all()

    def yield_point(self, kind: str, target: str,
                    ready: Optional[Callable[[], bool]] = None) -> None:
        """Park the calling controlled thread at a schedule point; the
        op it is about to perform executes after the grant, atomically
        up to its next yield. Uncontrolled threads fall through (their
        op runs unscheduled — controlled primitives are meant to be
        touched only by controlled threads)."""
        task = self.current_task()
        if task is None:
            return
        if self.aborted:
            raise Killed()
        with self._cv:
            task.pending = (kind, target)
            task.ready = ready
            task.state = "parked"
            self._cv.notify_all()
        task.gate.wait()
        task.gate.clear()
        if self.aborted:
            raise Killed()

    def advance_clock(self, dt: float) -> None:
        self.clock += max(dt, 0.0)

    def on_sleep(self, seconds: float) -> None:
        self.advance_clock(min(seconds, 0.05))
        self.yield_point("sleep", f"{seconds:g}")

    # -- queries (invariants run at quiescence) ----------------------------

    def lock_free(self, name: str) -> bool:
        """True when no instance of the named lock/rlock/condition is
        held — the guard harness invariants use before reading state
        the lock protects."""
        return all(p._free() for p in self.prims.get(name, ())
                   if hasattr(p, "_free"))

    # -- time patching ------------------------------------------------------

    def _patch_time(self) -> None:
        time.monotonic = _ctl_monotonic
        time.perf_counter = _ctl_perf_counter
        time.sleep = _ctl_sleep
        self._patched = True

    def _unpatch_time(self) -> None:
        if not self._patched:
            return
        # restore only what is still ours (the sanitizer's discipline)
        if time.monotonic is _ctl_monotonic:
            time.monotonic = _REAL_MONOTONIC
        if time.perf_counter is _ctl_perf_counter:
            time.perf_counter = _REAL_PERF
        if time.sleep is _ctl_sleep:
            time.sleep = _REAL_SLEEP
        self._patched = False

    # -- findings -----------------------------------------------------------

    def _record(self, kind: str, detail: str) -> None:
        if self.finding is None:
            self.finding = {
                "kind": kind,
                "step": self.steps,
                "detail": detail,
                "trace_tail": [" ".join(map(str, t))
                               for t in self.trace[-40:]],
            }

    # -- the scheduler loop -------------------------------------------------

    def explore(self, machine) -> "Controller":
        """Run one schedule of `machine` (an object with .run(ctl) and
        optional .invariant(ctl)/.final(ctl)). Returns self; the
        outcome is in .finding / .completed / .trace."""
        global _active
        if _active is not None:
            raise RuntimeError("a Controller is already installed")
        _active = self
        self._patch_time()
        # The machines deliberately drive failure paths thousands of
        # times (failover rescues, registry refusals): the serve
        # logger's per-event lines would dominate the run's wall time
        # and drown the explorer's own report.
        import logging

        serve_log = logging.getLogger("distributedmnist_tpu")
        prev_level = serve_log.level
        serve_log.setLevel(logging.CRITICAL)
        try:
            root = self.new_thread(lambda: machine.run(self),
                                   name="root", daemon=True)
            root.start()
            self._loop(machine)
        finally:
            self._shutdown()
            self._unpatch_time()
            serve_log.setLevel(prev_level)
            _active = None
        if self.finding is None:
            for task in self.tasks:
                if task.exc is not None:
                    tb = "".join(traceback.format_exception(
                        type(task.exc), task.exc,
                        task.exc.__traceback__)).strip()
                    self._record(
                        "exception",
                        f"thread {task.name!r} died: {tb.splitlines()[-1]}"
                        f"\n{tb}")
                    break
        if self.finding is None and self.completed:
            final = getattr(machine, "final", None)
            if callable(final):
                try:
                    final(self)
                except AssertionError as e:
                    self._record("invariant", f"final check: {e}")
        return self

    def _loop(self, machine) -> None:
        invariant = getattr(machine, "invariant", None)
        while True:
            granted_at = _REAL_MONOTONIC()
            with self._cv:
                while any(t.state == "running" for t in self.tasks):
                    if not self._cv.wait(timeout=0.5):
                        if _REAL_MONOTONIC() - granted_at > self.watchdog_s:
                            stuck = [t.name for t in self.tasks
                                     if t.state == "running"]
                            self._record(
                                "uninstrumented",
                                f"thread(s) {stuck} blocked outside the "
                                "controlled primitives (real lock/IO "
                                "under exploration?) — watchdog fired")
                            return
                parked = [t for t in self.tasks if t.state == "parked"]
                if not parked:
                    self.completed = True
                    return
            # quiescent: run the machine invariant, compute enablement
            if callable(invariant):
                try:
                    invariant(self)
                except AssertionError as e:
                    self._record("invariant", str(e))
                    return
            enabled = [t for t in parked if t.is_ready()]
            if not enabled:
                lines = [f"  {t.name}: waiting on {t.pending[0]} "
                         f"{t.pending[1]!r}" for t in parked]
                self._record(
                    "deadlock",
                    "no thread can make progress:\n" + "\n".join(lines))
                return
            if self.steps >= self.max_steps:
                self._record(
                    "budget",
                    f"step budget {self.max_steps} exhausted with "
                    f"{len(parked)} thread(s) still live")
                return
            enabled.sort(key=lambda t: t.tid)
            choice = self.policy.choose(self, enabled)
            if choice is None:
                # DFS sleep sets: this prefix only commutes independent
                # ops of an already-explored schedule — prune it.
                self.pruned = True
                return
            self.steps += 1
            self.clock += self.tick_s
            self.trace.append((self.steps, choice.name, *choice.pending))
            with self._cv:
                choice.state = "running"
            choice.gate.set()

    def _shutdown(self) -> None:
        """Release every still-parked thread with Killed and reap."""
        self.aborted = True
        with self._cv:
            live = [t for t in self.tasks if t.state != "finished"]
            for t in live:
                t.gate.set()
        deadline = _REAL_MONOTONIC() + 5.0
        for t in live:
            # bypass _ControlledThread.join — reaping must really wait
            # for the Killed unwind, not model a timeout
            threading.Thread.join(
                t.thread, timeout=max(deadline - _REAL_MONOTONIC(), 0.1))


# -- schedule policies -----------------------------------------------------


class RandomPolicy:
    """Seeded uniform choice among enabled threads: one seed, one
    schedule, deterministically replayable."""

    def __init__(self, seed: int):
        import random

        self.seed = seed
        self._rng = random.Random(seed)

    def choose(self, ctl: Controller, enabled: list) -> _Task:
        return enabled[self._rng.randrange(len(enabled))]


def _ops_independent(a: tuple, b: tuple) -> bool:
    """Name-based independence: two pending ops commute iff they
    target distinct primitives (lock/sem/FIFO/condition names, thread
    names for start/join). Conservative — same name is always treated
    as dependent."""
    return a[1] != b[1]


class DfsPolicy:
    """Bounded systematic DFS with SLEEP-SET partial-order reduction
    on independent primitive names (Godefroid's sleep sets: after a
    subtree is explored via op `o`, sibling subtrees keep `o` asleep
    until some DEPENDENT op — same primitive name — executes, so
    schedules that merely commute independent ops are explored once).
    Sleep sets preserve every deadlock and every terminal state; the
    per-step quiescent invariants additionally run on every explored
    schedule. Branching is at EVERY step with >= 2 awake threads —
    the reduction prunes the tree, it never starves an interleaving
    (the flaw a naive run-the-first-enabled reduction has).

    Persistent across schedules — Explorer drives begin_schedule()/
    end_schedule() and stops when .exhausted. choose() returns None
    when every enabled thread is asleep: the schedule prefix is
    redundant with an already-explored one and the controller prunes
    the run."""

    def __init__(self, por: bool = True):
        self.por = por
        self.exhausted = False
        # One node per choice depth along the current DFS path:
        # {"ops": [(name, op)] awake options, "chosen": int,
        #  "explored": [op]} — explored ops join the sleep set of
        # later siblings.
        self._stack: list = []
        self._depth = 0
        self._sleep: set = set()      # ops asleep at the current step

    def begin_schedule(self) -> None:
        self._depth = 0
        self._sleep = set()

    def choose(self, ctl: Controller, enabled: list) -> Optional[_Task]:
        by_name = {t.name: t for t in enabled}
        pend = {t.name: (t.pending[0], t.pending[1]) for t in enabled}
        if self.por:
            awake = [t for t in enabled
                     if (t.name, pend[t.name]) not in self._sleep]
        else:
            awake = list(enabled)
        if not awake:
            return None               # redundant prefix: prune
        if len(awake) == 1:
            chosen = awake[0]
        else:
            ops = [(t.name, pend[t.name]) for t in awake]
            if self._depth < len(self._stack):
                node = self._stack[self._depth]
                if node["ops"] != ops:
                    # enabled-set drift between replays would make the
                    # whole DFS meaningless — fail loudly
                    raise RuntimeError(
                        "DFS replay divergence: enabled set changed "
                        f"at depth {self._depth}: {node['ops']} vs "
                        f"{ops}")
            else:
                node = {"ops": ops, "chosen": 0, "explored": []}
                self._stack.append(node)
            self._depth += 1
            chosen = by_name[node["ops"][node["chosen"]][0]]
            # sleep-set propagation into the child: previously explored
            # siblings fall asleep; anything dependent on the chosen op
            # wakes up
            chosen_op = (chosen.name, pend[chosen.name])
            carried = self._sleep | {
                (nm, op) for nm, op in node["explored"]}
            self._sleep = {
                s for s in carried
                if _ops_independent(s[1], chosen_op[1])
                and s[0] != chosen.name}
            return chosen
        chosen_op = (chosen.name, pend[chosen.name])
        self._sleep = {
            s for s in self._sleep
            if _ops_independent(s[1], chosen_op[1])
            and s[0] != chosen.name}
        return chosen

    def end_schedule(self) -> None:
        while self._stack:
            node = self._stack[-1]
            if node["chosen"] + 1 < len(node["ops"]):
                node["explored"].append(node["ops"][node["chosen"]])
                node["chosen"] += 1
                return
            self._stack.pop()
        self.exhausted = True


# -- the explorer ----------------------------------------------------------


@dataclasses.dataclass
class MachineReport:
    """One machine's exploration summary — the ANALYSIS artifact row."""

    machine: str
    schedules: int = 0
    completed: int = 0
    pruned: int = 0
    budget_exhausted: int = 0
    steps_total: int = 0
    wall_s: float = 0.0
    base_seed: int = 0
    findings: list = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Explorer:
    """Drives N schedules of one machine factory and aggregates the
    findings. `factory()` must return a FRESH machine per schedule
    (state is rebuilt inside the controlled world every run)."""

    def __init__(self, max_steps: int = 20000,
                 stop_on_finding: bool = False):
        self.max_steps = max_steps
        self.stop_on_finding = stop_on_finding

    def run_one(self, factory, seed: int) -> Controller:
        ctl = Controller(policy=RandomPolicy(seed),
                         max_steps=self.max_steps)
        return ctl.explore(factory())

    def run(self, factory, name: str, schedules: int,
            base_seed: int = 0, policy: str = "random") -> MachineReport:
        report = MachineReport(machine=name, base_seed=base_seed)
        t0 = _REAL_MONOTONIC()
        dfs = DfsPolicy() if policy == "dfs" else None
        for i in range(schedules):
            if dfs is not None and dfs.exhausted:
                break
            seed = base_seed + i
            if dfs is not None:
                dfs.begin_schedule()
                ctl = Controller(policy=dfs, max_steps=self.max_steps)
                ctl.explore(factory())
                dfs.end_schedule()
            else:
                ctl = self.run_one(factory, seed)
            report.schedules += 1
            report.steps_total += ctl.steps
            if ctl.completed:
                report.completed += 1
            if ctl.pruned:
                report.pruned += 1
            if ctl.finding is not None:
                f = dict(ctl.finding)
                f["machine"] = name
                f["seed"] = seed
                f["policy"] = policy
                if f["kind"] == "budget":
                    report.budget_exhausted += 1
                else:
                    report.findings.append(f)
                    if self.stop_on_finding:
                        break
        report.wall_s = round(_REAL_MONOTONIC() - t0, 3)
        return report


def replay(factory, seed: int, max_steps: int = 20000) -> Controller:
    """Re-run the exact schedule a seed produced: same policy choices,
    same logical clock, same interleaving — the finding a failing seed
    reported reproduces identically (the replay-determinism test pins
    this)."""
    return Explorer(max_steps=max_steps).run_one(factory, seed)


# -- CLI -------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedmnist_tpu.analysis.explore",
        description="Deterministic schedule explorer over the four "
                    "riskiest serve state machines (cache single-flight "
                    "vs promote epoch, registry promote/rollback/"
                    "eviction, batcher submit/shed/drain/stop, fleet "
                    "pick/failover/drain-rejoin). Exit 0 clean, 1 on "
                    "findings.")
    p.add_argument("--machines",
                   default="cache,registry,batcher,batcher-nodrain,"
                           "fleet,scheduler-wfq,autoscaler-loop",
                   help="comma-separated machine names (default: all)")
    p.add_argument("--schedules", type=int, default=500,
                   help="schedules per machine (default 500 — the "
                       "scripts/explore.sh long budget)")
    p.add_argument("--seed", type=int, default=0,
                   help="base seed; schedule i runs seed+i")
    p.add_argument("--max-steps", type=int, default=20000)
    p.add_argument("--policy", choices=("random", "dfs"),
                   default="random")
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 preset: fixed seeds, a small bounded "
                        "budget per machine (<= 30 s total)")
    p.add_argument("--emit", action="store_true",
                   help="write an ANALYSIS_r*.json artifact (BENCH-"
                        "style round numbering)")
    p.add_argument("--stop-on-finding", action="store_true")
    args = p.parse_args(argv)

    from distributedmnist_tpu.analysis import harnesses

    if args.smoke:
        args.schedules = min(args.schedules, harnesses.SMOKE_SCHEDULES)
    names = [m.strip() for m in args.machines.split(",") if m.strip()]
    unknown = [m for m in names if m not in harnesses.MACHINES]
    if unknown:
        print(f"explore: unknown machine(s) {unknown}; known: "
              f"{sorted(harnesses.MACHINES)}", file=sys.stderr)
        return 2
    ex = Explorer(max_steps=args.max_steps,
                  stop_on_finding=args.stop_on_finding)
    reports = []
    for name in names:
        rep = ex.run(harnesses.MACHINES[name], name,
                     schedules=args.schedules, base_seed=args.seed,
                     policy=args.policy)
        reports.append(rep)
        status = ("CLEAN" if not rep.findings else
                  f"{len(rep.findings)} FINDING(S)")
        budget = (f", {rep.budget_exhausted} budget-exhausted"
                  if rep.budget_exhausted else "")
        print(f"explore: {name:<9} {rep.schedules} schedules "
              f"({rep.completed} completed{budget}, "
              f"{rep.steps_total} steps, {rep.wall_s:.1f}s) — {status}",
              flush=True)
        for f in rep.findings:
            print(f"  [{f['kind']}] seed={f['seed']} step={f['step']}: "
                  f"{f['detail'].splitlines()[0]}")
            if args.policy == "dfs":
                # DFS schedules are driven by the DFS stack, not the
                # seed: replay by re-running the deterministic DFS
                # sequence up to (and including) the failing schedule.
                nth = f["seed"] - args.seed + 1
                print(f"    replay: python -m distributedmnist_tpu"
                      f".analysis.explore --machines {name} "
                      f"--policy dfs --schedules {nth} "
                      "--stop-on-finding")
            else:
                print(f"    replay: python -m distributedmnist_tpu"
                      f".analysis.explore --machines {name} "
                      f"--schedules 1 --seed {f['seed']}")
    total_findings = sum(len(r.findings) for r in reports)
    # A machine whose every schedule blew the step budget proved
    # NOTHING — that must never read as a clean gate.
    no_coverage = [r.machine for r in reports
                   if r.schedules and r.completed == 0]
    if no_coverage:
        print(f"explore: machine(s) {no_coverage} completed ZERO "
              "schedules (step budget exhausted?) — no coverage, "
              "failing the gate", file=sys.stderr)
    if args.emit:
        from distributedmnist_tpu.analysis import report as report_mod

        path = report_mod.emit_analysis({
            "kind": "explorer",
            "policy": args.policy,
            "base_seed": args.seed,
            "schedules_per_machine": args.schedules,
            "machines": [r.as_dict() for r in reports],
            "total_findings": total_findings,
        })
        print(f"explore: artifact written to {path}")
    return 1 if (total_findings or no_coverage) else 0


if __name__ == "__main__":
    # runpy executes this file under the name "__main__": delegate to
    # the CANONICAL module object so there is exactly one `_active`
    # controller global — the one the locks.py factories read. Running
    # the __main__ copy's main() directly would install the controller
    # in a parallel module and hand every machine bare primitives.
    from distributedmnist_tpu.analysis import explore as _canonical

    sys.exit(_canonical.main())
