"""`python -m distributedmnist_tpu.analysis` — run the project lint
(scripts/lint.sh is the shell wrapper scripts/tier1.sh invokes)."""

import sys

from distributedmnist_tpu.analysis.lint import main

sys.exit(main())
