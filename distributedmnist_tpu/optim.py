"""Optimizer factory — the reference's SGD and Adam
[BASELINE.json configs 1 (SGD) and 2/4/5 (Adam); SURVEY.md §2 rows 4-5].

optax transforms are pure pytree->pytree functions, so the optimizer update
compiles into the same fused XLA program as forward/backward/psum — there is
no separate "optimizer.step()" host call as in the reference's hot loop
(SURVEY.md §3.1 vs §3.2).
"""

from __future__ import annotations

import optax


def build(name: str, learning_rate: float, momentum: float = 0.9
          ) -> optax.GradientTransformation:
    if name == "sgd":
        return optax.sgd(learning_rate, momentum=momentum)
    if name == "adam":
        return optax.adam(learning_rate)
    raise ValueError(f"unknown optimizer {name!r} (expected sgd|adam)")
