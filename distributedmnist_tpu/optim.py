"""Optimizer factory — the reference's SGD and Adam
[BASELINE.json configs 1 (SGD) and 2/4/5 (Adam); SURVEY.md §2 rows 4-5],
plus optional learning-rate schedules (beyond parity — they shorten
wall-clock-to-99%, the headline metric).

optax transforms are pure pytree->pytree functions, so the optimizer update
compiles into the same fused XLA program as forward/backward/psum — there is
no separate "optimizer.step()" host call as in the reference's hot loop
(SURVEY.md §3.1 vs §3.2). Schedules are step->lr functions traced into that
same program (the step counter lives in the optimizer state on device).
"""

from __future__ import annotations

from typing import Optional

import optax


def make_schedule(learning_rate: float, schedule: str = "constant",
                  warmup_steps: int = 0,
                  total_steps: Optional[int] = None):
    """step -> lr. {constant, cosine, warmup-cosine}; cosine decays to 0
    over total_steps (required for the cosine variants)."""
    if schedule == "constant":
        if warmup_steps:
            return optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return learning_rate
    if schedule in ("cosine", "warmup-cosine"):
        if not total_steps:
            raise ValueError(f"{schedule} schedule needs total_steps")
        if schedule == "warmup-cosine" and warmup_steps <= 0:
            raise ValueError(
                "warmup-cosine needs --warmup-steps > 0 (with 0 it would "
                "silently start at peak LR; use 'cosine' for that)")
        # warmup_steps is honored by every schedule ("cosine" with warmup
        # is identical to "warmup-cosine"; the alias exists for CLI
        # symmetry with "constant").
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else learning_rate,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=total_steps)
    raise ValueError(f"unknown lr schedule {schedule!r}")


def build(name: str, learning_rate, momentum: float = 0.9,
          flat: bool = False) -> optax.GradientTransformation:
    """`learning_rate` may be a float or an optax schedule (step -> lr).

    flat=True wraps the transform in optax.flatten: grads are raveled
    into ONE contiguous vector before the update and the updates
    unraveled after, so the optimizer state is a single vector per moment
    and the whole update is one fused elementwise XLA op instead of
    dozens of per-leaf ops (measured 0.15 ms/step at batch 512 on the
    v5e — scripts/profile_step.py). Elementwise transforms are
    concatenation-invariant, so trajectories are bit-identical (pinned
    by tests/test_packing.py). Note the optimizer STATE pytree differs
    between flat and non-flat runs, so checkpoints are format-specific.
    """
    if name == "sgd":
        tx = optax.sgd(learning_rate, momentum=momentum)
    elif name == "adam":
        tx = optax.adam(learning_rate)
    else:
        raise ValueError(f"unknown optimizer {name!r} (expected sgd|adam)")
    return optax.flatten(tx) if flat else tx
