"""Trainer: the fused XLA training step and the host-side fit loop.

Reference parity [BASELINE.json north_star]: "the LeNet/MLP forward-backward
becomes a jax.jit-compiled step function, the per-step NCCL gradient
allreduce maps to lax.psum over a named ICI device mesh". The reference's
hot loop (SURVEY.md §3.1) runs forward / backward / NCCL-allreduce /
optimizer.step as four host-driven phases; here all four are ONE compiled
XLA program (SURVEY.md §3.2) and the host only dispatches.

Two SPMD modes, equivalence-tested against each other:

- "auto": `jax.jit` with sharded inputs — the batch arrives sharded over
  'data', params replicated; XLA's sharding propagation inserts the gradient
  all-reduce. The modern idiomatic form.
- "explicit": `shard_map` with a hand-written `lax.pmean(grads, 'data')` —
  the literal TPU translation of the reference's per-step allreduce, kept
  both as documentation of where the collective lives and as a test oracle.

The batch is selected ON DEVICE: the step takes the full device-resident
uint8 dataset plus a sharded index array, gathers, normalizes, and the
gather/normalize fuse into the first conv/matmul. No pixels cross the host
boundary in the hot loop.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data import DeviceDataset, IndexStream, load_mnist
from distributedmnist_tpu.data.loader import eval_batches
from distributedmnist_tpu.ops import accuracy_count, cross_entropy
from distributedmnist_tpu.parallel import (
    distributed, get_devices, make_mesh, tp)
from distributedmnist_tpu.parallel.mesh import DATA_AXIS
from distributedmnist_tpu.utils import MetricsLogger, StepTimer, round_up

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

log = logging.getLogger("distributedmnist_tpu")


class SimulatedFailure(RuntimeError):
    """Raised by the --fail-at-step fault-injection hook (SURVEY.md §5)."""


class TrainState(struct.PyTreeNode):
    step: jax.Array            # int32 scalar
    params: Any
    opt_state: Any


def init_state(rng: jax.Array, model, tx: optax.GradientTransformation,
               sample: jax.Array) -> TrainState:
    params = model.init(rng, sample)["params"]
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=tx.init(params))


def _decoder(pixel_format: str, dtype):
    """raw gathered rows -> normalized (B, 28, 28, 1) images. 'u8' rows
    are byte images; 'packed' rows are (B, 196) int32 words, 4 pixels per
    word (data/packing.py — the packed gather is ~free on the TPU where
    the uint8 gather costs ~0.11 ms/step at batch 512)."""
    if pixel_format == "u8":
        def decode(x_u8):
            return x_u8.astype(dtype) / jnp.asarray(255.0, dtype)
    elif pixel_format == "packed":
        from distributedmnist_tpu.data.packing import unpack_rows

        def decode(words):
            return unpack_rows(words, dtype)
    else:
        raise ValueError(f"unknown pixel format {pixel_format!r}")
    return decode


def _forward_loss(model, dtype, pixel_format: str = "u8"):
    decode = _decoder(pixel_format, dtype)

    def loss_fn(params, x_raw, y):
        logits = model.apply({"params": params}, decode(x_raw))
        return cross_entropy(logits, y)
    return loss_fn


def _apply_update(tx, state, grads):
    """optimizer update -> next TrainState. The single spelling of the
    update shared by both SPMD modes and the grad-accum branches."""
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(step=state.step + 1, params=params,
                      opt_state=opt_state)


def _make_one_step(loss_fn, tx):
    """grad -> optimizer update -> new state, for one (x, y) batch."""
    def one_step(state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, x, y)
        return _apply_update(tx, state, grads), loss
    return one_step


def _accumulate_grads(loss_fn, params, micro_batches, grad_accum):
    """Mean loss and gradients over `grad_accum` microbatches, via an inner
    lax.scan. micro_batches is a callable i -> (x, y) producing the i-th
    microbatch (already sharded); equal microbatch sizes make the mean of
    microbatch means the exact full-batch gradient."""
    def micro(carry, i):
        g_acc, l_acc = carry
        x, y = micro_batches(i)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        return (jax.tree.map(jnp.add, g_acc, grads), l_acc + loss), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (g_sum, l_sum), _ = jax.lax.scan(
        micro, (zeros, jnp.zeros((), jnp.float32)),
        jnp.arange(grad_accum))
    inv = 1.0 / grad_accum
    return l_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)


def make_train_step(model, tx, mesh, mode: str = "auto",
                    dtype=jnp.float32, grad_accum: int = 1,
                    pixel_format: str = "u8"):
    """Build the jitted train step: (state, train_x, train_y, idx_block) ->
    (state, metrics).

    `idx_block` has shape (K, global_batch) — K optimizer steps fused into
    ONE XLA dispatch via `lax.scan` (the TPU superstep: a single MNIST step
    is ~100µs, so at K=1 host dispatch dominates wall-clock; scanning K
    steps amortizes it K-fold). The leading K axis is scanned; the batch
    axis is sharded over 'data'. The dataset arrays are replicated.
    metrics = {"loss": last-step loss, "loss_mean": mean over the block}.

    grad_accum > 1 splits each optimizer step's global batch into that
    many microbatches, accumulating gradients in an inner scan before the
    single optimizer update. Each microbatch is itself sharded over 'data'
    (the gather source is replicated, so microbatching adds no
    communication); in explicit mode the gradient allreduce still happens
    ONCE per optimizer step, after accumulation — the classic
    communication win of accumulation.
    """
    loss_fn = _forward_loss(model, dtype, pixel_format)
    one_step = _make_one_step(loss_fn, tx)

    if mode == "auto":
        batch_spec = NamedSharding(mesh, P(DATA_AXIS))

        def _gather(train_x, train_y, idx):
            x = jax.lax.with_sharding_constraint(
                jnp.take(train_x, idx, axis=0), batch_spec)
            y = jax.lax.with_sharding_constraint(
                jnp.take(train_y, idx, axis=0), batch_spec)
            return x, y

        def _block(state, train_x, train_y, idx_block):
            def body(state, idx):
                if grad_accum == 1:
                    return one_step(state, *_gather(train_x, train_y, idx))
                idx_m = idx.reshape(grad_accum, -1)
                loss, grads = _accumulate_grads(
                    loss_fn, state.params,
                    lambda i: _gather(train_x, train_y, idx_m[i]),
                    grad_accum)
                return _apply_update(tx, state, grads), loss

            state, losses = jax.lax.scan(body, state, idx_block)
            return state, {"loss": losses[-1], "loss_mean": losses.mean()}

        return jax.jit(_block, donate_argnums=0)

    if mode != "explicit":
        raise ValueError(f"unknown spmd mode {mode!r}")
    return _make_explicit_step(loss_fn, tx, mesh, grad_accum)


def make_train_step_from_batches(model, tx, mesh, dtype=jnp.float32):
    """Train step consuming pre-gathered batches from the streaming host
    pipeline (data/host_loader.HostStream): (state, x_block, y_block) ->
    (state, metrics), x_block (K, B, 28, 28, 1) sharded P(None, 'data').
    Used when the dataset can't live device-resident; the scan/metrics
    semantics match make_train_step exactly."""
    one_step = _make_one_step(_forward_loss(model, dtype), tx)
    batch_spec = NamedSharding(mesh, P(DATA_AXIS))

    def _block(state, x_block, y_block):
        def body(state, xy):
            x, y = xy
            x = jax.lax.with_sharding_constraint(x, batch_spec)
            y = jax.lax.with_sharding_constraint(y, batch_spec)
            return one_step(state, x, y)

        state, losses = jax.lax.scan(body, state, (x_block, y_block))
        return state, {"loss": losses[-1], "loss_mean": losses.mean()}

    return jax.jit(_block, donate_argnums=0)


def _make_explicit_step(loss_fn, tx, mesh, grad_accum: int = 1):
    # explicit: the reference's per-step gradient allreduce, spelled out as
    # lax.pmean over the named 'data' axis inside shard_map [north_star].
    def _local_block(state, train_x, train_y, idx_block):
        def body(state, idx):             # idx is the LOCAL shard here
            if grad_accum == 1:
                loss, grads = jax.value_and_grad(loss_fn)(
                    state.params, jnp.take(train_x, idx, axis=0),
                    jnp.take(train_y, idx, axis=0))
            else:
                idx_m = idx.reshape(grad_accum, -1)
                loss, grads = _accumulate_grads(
                    loss_fn, state.params,
                    lambda i: (jnp.take(train_x, idx_m[i], axis=0),
                               jnp.take(train_y, idx_m[i], axis=0)),
                    grad_accum)
            # Equal shard sizes (enforced at config time) make
            # pmean-of-means the exact global mean. With accumulation the
            # allreduce still happens once per optimizer step.
            grads = jax.lax.pmean(grads, DATA_AXIS)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            return _apply_update(tx, state, grads), loss

        state, losses = jax.lax.scan(body, state, idx_block)
        return state, {"loss": losses[-1], "loss_mean": losses.mean()}

    specs = dict(mesh=mesh,
                 in_specs=(P(), P(), P(), P(None, DATA_AXIS)),
                 out_specs=(P(), P()))
    try:
        smapped = shard_map(_local_block, check_vma=False, **specs)
    except TypeError:
        # jax < 0.6 spells the replication-check knob check_rep; newer
        # versions renamed it to check_vma and dropped the old name.
        smapped = shard_map(_local_block, check_rep=False, **specs)
    return jax.jit(smapped, donate_argnums=0)


def make_eval_fn(model, mesh, dtype=jnp.float32):
    """Jitted full-test-set accuracy: scan over index batches, each batch
    sharded over 'data' (inputs arrive pre-sharded); the correct-count
    reduction crosses devices via an XLA-inserted psum. Returns the int32
    number of correct predictions."""
    del mesh  # placement comes entirely from the pre-sharded inputs

    def _eval(params, test_x, test_y, idx_mat, mask_mat):
        def body(correct, xs):
            idx, mask = xs
            x = jnp.take(test_x, idx, axis=0).astype(dtype) / 255.0
            y = jnp.take(test_y, idx, axis=0)
            logits = model.apply({"params": params}, x)
            return correct + accuracy_count(logits, y, mask), None

        correct, _ = jax.lax.scan(body, jnp.zeros((), jnp.int32),
                                  (idx_mat, mask_mat))
        return correct

    return jax.jit(_eval)


def _pick_steps_per_call(cfg: Config, platform: str, has_ckpt: bool,
                         streaming: bool = False) -> int:
    """Steps fused per XLA dispatch. Auto: 1 on CPU (synchronous, small
    thread pool); on TPU the largest k <= 1024 dividing the eval/
    checkpoint cadence, so block edges land exactly on eval and
    checkpoint steps. (lax.scan compiles its body once, so compile time
    is k-independent. The ceiling was 256 through round 4; same-window
    bench measurements found throughput still rising to k~1024 at b=512
    — a 256-step block's ~125 ms of device time sits right at one relay
    RTT, so per-block fetch costs leak in below that. The cadence
    divisor rule still binds first for typical eval_every values.)

    The STREAMING pipeline keeps the 256 ceiling: each of its dispatched
    blocks materializes a full (k, B, ...) input array on device and the
    bounded in-flight window keeps up to max_inflight of them live —
    quadrupling k quadruples queued-input HBM on exactly the pipeline
    that exists for datasets too big to sit in HBM. The device-resident
    pipeline's blocks carry only (k, B) int32 indices, where deep is
    free."""
    if cfg.steps_per_call is not None:
        return max(1, cfg.steps_per_call)
    if platform == "cpu":
        return 1
    import math
    cadence = cfg.eval_every
    if has_ckpt:
        cadence = math.gcd(cadence, cfg.checkpoint_every)
    if cfg.fail_at_step:
        cadence = math.gcd(cadence, cfg.fail_at_step)
    for k in range(min(256 if streaming else 1024, cadence), 0, -1):
        if cadence % k == 0:
            return k
    return 1


def fit(cfg: Config, data: Optional[dict] = None) -> dict:
    """Run one training workload end-to-end; returns the summary dict whose
    JSON form is the driver-facing result (SURVEY.md §2 row 11).

    With a checkpoint_dir and graceful_preemption (the default), a SIGTERM
    during training stops the run early and force-saves a resumable
    checkpoint; the absorbed signal is reported as summary["preempted"],
    NOT re-delivered. A caller that would run further work after fit()
    must check that flag and wind down instead."""
    from distributedmnist_tpu.checkpoint import Checkpointer  # lazy: orbax
    from distributedmnist_tpu.utils import enable_compilation_cache

    # Rendezvous BEFORE enabling the compile cache: the cache helper
    # gives each process of a multi-process run its own subdirectory
    # (shared-dir corruption — see utils/compile_cache.py), and it can
    # only know the process index once jax.distributed is live.
    multihost = distributed.maybe_initialize(
        cfg.coordinator_address, cfg.num_processes, cfg.process_id)
    enable_compilation_cache()
    devices = get_devices(cfg.device, cfg.num_devices)
    n_chips = len(devices)
    mp = cfg.model_parallel
    if mp > 1 and cfg.spmd_mode == "explicit":
        raise ValueError("model_parallel > 1 requires spmd_mode=auto "
                         "(the explicit shard_map path is DP-only)")
    if n_chips % mp:
        raise ValueError(
            f"{n_chips} chips not divisible by model_parallel={mp}")
    dp_size = n_chips // mp
    if cfg.batch_size % dp_size:
        raise ValueError(
            f"global batch {cfg.batch_size} not divisible by "
            f"{dp_size} data-parallel chips")
    ga = cfg.grad_accum
    if ga < 1:
        raise ValueError(f"grad_accum must be >= 1, got {ga}")
    if ga > 1 and cfg.batch_size % (dp_size * ga):
        raise ValueError(
            f"global batch {cfg.batch_size} not divisible by "
            f"{dp_size} chips x {ga} grad-accum microbatches")
    mesh = make_mesh(devices, mp)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32

    if cfg.data_pipeline not in ("device", "stream"):
        raise ValueError(f"unknown data pipeline {cfg.data_pipeline!r}")
    streaming = cfg.data_pipeline == "stream"
    if streaming and cfg.spmd_mode == "explicit":
        raise ValueError("data_pipeline=stream requires spmd_mode=auto")
    if streaming and ga > 1:
        raise ValueError("grad_accum > 1 requires the device-resident "
                         "pipeline (microbatches re-gather from the "
                         "replicated dataset; pre-gathered streamed "
                         "batches would reshard on every split)")
    data = data if data is not None else load_mnist(
        cfg.data_dir, cfg.synthetic, cfg.seed)
    # The packed layout only exists device-resident (streamed batches
    # arrive as images); resolve the effective pixel format here.
    pixel_format = "u8" if streaming else cfg.pixel_format
    # Eval-only never touches train data: skip its device placement too.
    ds = DeviceDataset(
        data, mesh,
        device_resident_train=not streaming and not cfg.eval_only,
        pixel_format=pixel_format)

    # TP shards whole params across 'model'; the Pallas kernel is written
    # for unsharded operands, so TP runs force the XLA dense path.
    fused = "xla" if mp > 1 else cfg.fused_kernels
    model = models.build(cfg.model, dtype=dtype, fused=fused,
                         platform=devices[0].platform, conv=cfg.conv_impl)
    steps_per_epoch = ds.train_n // cfg.batch_size
    total_steps = cfg.steps if cfg.steps is not None \
        else cfg.epochs * steps_per_epoch
    # Decay horizon: the run's own length unless pinned — lr_decay_steps
    # keeps a tuned cosine recipe's curve invariant to the budget knobs
    # (--max-epochs/--steps), which otherwise silently reshape it.
    if cfg.lr_decay_steps is not None and cfg.lr_decay_steps < 1:
        raise ValueError(
            f"lr_decay_steps must be >= 1, got {cfg.lr_decay_steps} "
            "(omit it to decay over the run's own length)")
    lr = optim.make_schedule(cfg.learning_rate, cfg.lr_schedule,
                             cfg.warmup_steps,
                             total_steps if cfg.lr_decay_steps is None
                             else cfg.lr_decay_steps)
    # TP shards optimizer moments by leaf name (parallel/tp.py); the flat
    # update's single-vector state can't be, so TP forces per-leaf.
    tx = optim.build(cfg.optimizer, lr, cfg.momentum,
                     flat=cfg.flat_optimizer and mp == 1)
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    state = init_state(rng, model, tx, sample)
    # Placement IS the parallelism: replicated under pure DP, Megatron-style
    # specs under TP (parallel/tp.py); the step function never changes.
    state = jax.device_put(state, tp.state_shardings(state, mesh, cfg.model))

    if cfg.eval_only and not (cfg.checkpoint_dir and cfg.resume):
        raise ValueError(
            "--eval-only needs a restorable checkpoint "
            "(--checkpoint-dir with an existing checkpoint and "
            "resume enabled)")

    ckpt = None
    restored = False
    if cfg.checkpoint_dir:
        ckpt = Checkpointer(cfg.checkpoint_dir)
        if cfg.resume:
            state, restored = ckpt.maybe_restore(state)
            if restored:
                log.info("restored checkpoint at step %d", int(state.step))

    start_step = int(state.step)
    if cfg.eval_only:
        if not restored:
            ckpt.close()   # don't leak the async manager on the error path
            raise ValueError(
                "--eval-only: no checkpoint found to restore in "
                f"{cfg.checkpoint_dir!r}")
        # Evaluate the restored state and skip the training loop: the
        # loop below is a no-op when total_steps == start_step, and the
        # summary's closing eval produces the accuracy.
        total_steps = start_step
        run_block = None       # never called: the loop body is unreachable
    elif streaming:
        from distributedmnist_tpu.data.host_loader import HostStream
        stream = HostStream(data["train_x"], data["train_y"],
                            cfg.batch_size, cfg.seed, mesh,
                            start_step=start_step,
                            source=cfg.stream_source)
        step_fn = make_train_step_from_batches(model, tx, mesh, dtype)

        def run_block(state, k):
            return step_fn(state, *stream.next_block(k))
    else:
        stream = IndexStream(ds.train_n, cfg.batch_size, cfg.seed, mesh,
                             start_step=start_step)
        step_fn = make_train_step(model, tx, mesh, cfg.spmd_mode, dtype,
                                  grad_accum=ga,
                                  pixel_format=pixel_format)

        def run_block(state, k):
            return step_fn(state, ds.train_x, ds.train_y,
                           stream.next_block(k))
    eval_fn = make_eval_fn(model, mesh, dtype)
    eb = round_up(min(2048, ds.test_n), n_chips)
    idx_mat, mask_mat = eval_batches(ds.test_n, eb)
    eval_spec = NamedSharding(mesh, P(None, DATA_AXIS))
    idx_mat = distributed.put_global(idx_mat, eval_spec)
    mask_mat = distributed.put_global(mask_mat, eval_spec)

    def drain_inflight() -> None:
        """Finish (and COUNT) every queued training block before entering
        an excluded span. Device programs execute in order, so an eval/
        checkpoint/allgather fetch inside timer.exclude() would otherwise
        wait out the queued blocks' device time there — silently moving
        real training compute into `excluded` and inflating the reported
        throughput (observed: a 16-blocks-in-flight run whose only eval
        sat at the end reported a physically impossible img/s). One fetch
        of the NEWEST block suffices: blocks chain through the donated
        state, so its value covers every queued predecessor (the same
        argument bench.py's closing fetch rests on) — fetching each block
        separately would charge one relay round-trip per block."""
        if inflight:
            StepTimer.barrier(inflight[-1])
            inflight.clear()

    n_evals = [0]

    def evaluate(state) -> float:
        # Inside timer.exclude(): eval seconds must not deflate the
        # training-throughput metric (the BASELINE headline number) —
        # but the queued TRAIN blocks ahead of it must finish on the
        # counted clock first.
        drain_inflight()
        n_evals[0] += 1
        with timer.exclude():
            correct = eval_fn(state.params, ds.test_x, ds.test_y,
                              idx_mat, mask_mat)
            return float(correct) / ds.test_n

    # Bound async dispatch depth: JAX dispatch is async, so without a cap
    # the host can enqueue hundreds of concurrent executions. On TPU a deep
    # window keeps the pipeline full; on the CPU backend concurrent
    # programs containing collectives can starve the (num_cores-sized)
    # thread pool and deadlock the all-reduce rendezvous, so cap at 1.
    if cfg.max_inflight is not None:
        max_inflight = cfg.max_inflight
    elif devices[0].platform == "cpu":
        max_inflight = 1
    else:
        max_inflight = 16
    inflight: deque = deque()

    timer = StepTimer(cfg.batch_size, n_chips)
    mlog = MetricsLogger()
    t_start = time.perf_counter()
    accuracy = 0.0
    metrics = None
    reached_target_at: Optional[float] = None
    profiling = False
    if cfg.profile_dir and jax.process_index() == 0:
        jax.profiler.start_trace(cfg.profile_dir)
        profiling = True

    spc = _pick_steps_per_call(cfg, devices[0].platform, bool(ckpt),
                               streaming=streaming)

    def crossed(step_before: int, step_after: int, every: int) -> bool:
        return step_after // every > step_before // every

    # Graceful preemption (SURVEY.md §5 failure recovery, beyond the
    # --fail-at-step injection): a SIGTERM — the warning real schedulers
    # deliver before killing a worker — stops training and force-saves a
    # checkpoint at the exact stopping step instead of dropping progress
    # since the last periodic save. Installed only when there is a
    # checkpointer to save with and we're on the main thread
    # (signal.signal is main-thread-only).
    #
    # Single-process: stop at the next block boundary. Multi-process:
    # Checkpointer.save is a cross-process collective, so a process must
    # NEVER stop unilaterally on its local signal (the others would hang
    # in the save barrier, or save a different step). The local flags are
    # all-gathered at every eval/checkpoint boundary — steps all
    # processes reach deterministically — and ALL processes stop iff ANY
    # process was signalled, so the force-save below lines up
    # process-for-process at the same step. If no boundary remains before
    # total_steps, the run simply completes — at most eval_every steps
    # away — with the handler still deferring the signal past the final
    # force-save.
    import signal
    import threading
    n_proc = jax.process_count()
    preempt_signum = [None]
    preempt_agreed = [False]
    sigterm_installed = False
    # start_step < total_steps: an eval-only or already-complete run has
    # no loop to stop and no progress to save — absorbing SIGTERM there
    # would only make the process immune to termination. (Deterministic
    # and identical across processes, so the exchange stays symmetric.)
    install = (ckpt is not None and cfg.graceful_preemption
               and start_step < total_steps
               and threading.current_thread() is threading.main_thread())
    if n_proc > 1:
        # The per-boundary flag exchange is a collective: every process
        # must join or none may. Agree ONCE at startup whether all
        # processes CAN install the handler — a non-main-thread fit() or
        # --no-graceful-preemption on one host must not leave the others
        # blocked in an allgather the missing process never joins. This
        # runs unconditionally under n_proc > 1 for the same reason, and
        # BEFORE any handler is installed: if the exchange itself raises,
        # no custom disposition leaks past fit(), and a SIGTERM during
        # the exchange terminates under the pre-existing disposition
        # (nothing is saved yet, so that is the right outcome).
        # agree_max over the live mesh (NOT multihost_utils.process_
        # allgather, which builds a fresh mesh per call and segfaults on
        # some multi-process CPU backends — parallel/distributed.py):
        # "all capable" == no process reports incapable.
        all_capable = distributed.agree_max(
            0 if install else 1, mesh) == 0
        if install and not all_capable:
            log.warning("graceful preemption disabled: not every process "
                        "can install the SIGTERM handler")
        install = install and all_capable
    if install:
        def _on_sigterm(signum, frame):
            preempt_signum[0] = signum
        prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        sigterm_installed = True

    def stop_requested() -> bool:
        if not sigterm_installed:
            return False
        if n_proc == 1:
            return preempt_signum[0] is not None
        return preempt_agreed[0]

    step = start_step
    first_call = True
    try:
        try:
            while step < total_steps:
                if stop_requested():
                    log.info("SIGTERM: stopping at step %d to checkpoint",
                             step)
                    break
                k = min(spc, total_steps - step)  # remainder block
                                                  # recompiles once; only
                                                  # at the very end
                # Block BEFORE dispatching so at most max_inflight
                # programs are ever concurrently in flight (cap 1 on CPU
                # really means 1). Drain via a value fetch: on
                # pooled/relay backends block_until_ready returns before
                # execution completes (StepTimer.barrier), which would
                # let queue depth grow unbounded here.
                while len(inflight) >= max_inflight:
                    StepTimer.barrier(inflight.popleft())
                state, metrics = run_block(state, k)
                inflight.append(metrics["loss"])
                prev, step = step, step + k
                if first_call:
                    timer.start(sync=metrics["loss"])  # excludes compile
                    first_call = False
                else:
                    timer.lap(k)
                if cfg.log_every and crossed(prev, step, cfg.log_every):
                    mlog.step(step, {"loss": metrics["loss"],
                                     "loss_mean": metrics["loss_mean"]})

                if (sigterm_installed and n_proc > 1
                        and (crossed(prev, step, cfg.checkpoint_every)
                             or crossed(prev, step, cfg.eval_every))):
                    # Drain first (counted): programs run in order, so
                    # the allgather's value fetch waits out the queued
                    # blocks anyway — and on CPU the collective must not
                    # race them in a small host thread pool.
                    drain_inflight()
                    with timer.exclude():
                        preempt_agreed[0] = distributed.agree_max(
                            0 if preempt_signum[0] is None else 1,
                            mesh) == 1

                if ckpt and crossed(prev, step, cfg.checkpoint_every):
                    # Same attribution rule: the save's device->host
                    # copy waits for the queued blocks' state; finish
                    # them on the counted clock, exclude only the copy
                    # (the disk write still overlaps training — async).
                    drain_inflight()
                    with timer.exclude():
                        ckpt.save(step, state)

                if (cfg.fail_at_step is not None
                        and step >= cfg.fail_at_step):
                    if ckpt:
                        ckpt.wait()
                    raise SimulatedFailure(
                        f"injected failure at step {step}")

                if crossed(prev, step, cfg.eval_every) \
                        or step == total_steps:
                    accuracy = evaluate(state)
                    mlog.eval(step, accuracy)
                    if (cfg.target_accuracy is not None
                            and accuracy >= cfg.target_accuracy):
                        reached_target_at = time.perf_counter() - t_start
                        log.info("target accuracy %.3f reached at step "
                                 "%d (%.2fs)", cfg.target_accuracy, step,
                                 reached_target_at)
                        break
        finally:
            if profiling:
                jax.profiler.stop_trace()

        # On preemption skip the closing eval (a collective — all
        # processes skip together, every term below being deterministic
        # or agreed): the grace period between SIGTERM and SIGKILL is for
        # the checkpoint save, not a test pass. A run that ran to
        # completion (or stopped on target accuracy) finished its job —
        # a signal that landed during the final block must not make an
        # orchestrator requeue it as preempted.
        preempted = (stop_requested() and step < total_steps
                     and reached_target_at is None)
        if accuracy == 0.0 and not preempted:
            accuracy = evaluate(state)
        throughput = timer.snapshot(sync=state.params)
        wall = time.perf_counter() - t_start

        if ckpt:
            ckpt.save(int(state.step), state, force=True)
            ckpt.wait()
            ckpt.close()
    finally:
        # Restored only AFTER the force-save above: a second SIGTERM
        # during the save must be absorbed by the handler, not kill the
        # process mid-write under the default disposition. An absorbed
        # signal is REPORTED (summary["preempted"]), not re-delivered —
        # re-raising here would kill the process before the summary/JSON
        # line the save exists to pair with; a caller that runs further
        # work after fit() must check the flag. signal.getsignal-style
        # None (a non-Python-installed prior handler) can't be passed
        # back to signal.signal — fall back to the default disposition.
        if sigterm_installed:
            signal.signal(signal.SIGTERM,
                          prev_sigterm if prev_sigterm is not None
                          else signal.SIG_DFL)

    summary = {
        "model": cfg.model,
        "optimizer": cfg.optimizer,
        "spmd_mode": cfg.spmd_mode,
        "n_chips": n_chips,
        "model_parallel": mp,
        "n_processes": jax.process_count(),
        "multihost": multihost,
        "global_batch": cfg.batch_size,
        "data": ds.source,
        "data_pipeline": cfg.data_pipeline,
        "pixel_format": pixel_format,
        "steps": int(state.step),
        "n_evals": n_evals[0],
        "restored": restored,
        "preempted": preempted,
        "test_accuracy": accuracy,
        "final_loss": (None if metrics is None
                       else float(jax.device_get(metrics["loss"]))),
        "target_accuracy": cfg.target_accuracy,
        "wall_clock_s": wall,
        "wall_clock_to_target_s": reached_target_at,
        **throughput,
    }
    log.info("summary %s", MetricsLogger.summary_line(summary))
    return summary
