"""Optional tensor parallelism over a 2-D ('data', 'model') mesh.

The reference has data parallelism only (SURVEY.md §2: "DP — the only
one"), so this is a beyond-parity capability, not a port: it exists to show
the mesh design generalizes past DP the TPU way. There is NO new step
function — the same jitted train step runs unchanged; tensor parallelism is
purely a change of parameter PLACEMENT (Megatron-style paired specs below),
and XLA's sharding propagation inserts the column/row-parallel collectives.

Pairing (for each dense pair A @ B):
  first kernel  P(None, 'model')   column-parallel: activations sharded
  its bias      P('model')
  second kernel P('model', None)   row-parallel: psum on the way out
Conv kernels and everything else stay replicated — at LeNet scale convs
have no use for TP; the dense tail is where the parameters are.

Optimizer state (adam mu/nu) mirrors the params tree, and the name-based
rules match on path components, so mu/nu leaves pick up the identical specs
for free.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("distributedmnist_tpu")

MODEL_AXIS = "model"


def _mlp_rule(names: set, ndim: int) -> P:
    # XLA path: params['hidden']['kernel'|'bias']; Pallas path names them
    # hidden_kernel / hidden_bias at the top level.
    if "hidden" in names or "hidden_kernel" in names or "hidden_bias" in names:
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    if "logits" in names and ndim == 2:
        return P(MODEL_AXIS, None)
    return P()


def _lenet_rule(names: set, ndim: int) -> P:
    if "fc1" in names:
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    if "fc2" in names and ndim == 2:
        return P(MODEL_AXIS, None)
    return P()


_RULES = {"mlp": _mlp_rule, "lenet": _lenet_rule}


def _path_names(path) -> set:
    names = set()
    for p in path:
        for attr in ("key", "name"):
            v = getattr(p, attr, None)
            if isinstance(v, str):
                names.add(v)
    return names


def state_shardings(state: Any, mesh: Mesh, model_name: str):
    """NamedSharding pytree for a TrainState under the given mesh.

    1-D mesh (no 'model' axis): everything replicated — the DP baseline.
    2-D mesh: the model's rules decide. A leaf whose sharded dim doesn't
    divide the 'model' axis size falls back to replicated WITH a warning;
    if every matched leaf fell back — or no leaf matched the rules at all
    (e.g. a layer rename broke the name-based matching) — the run would
    silently execute as pure DP, so that raises instead.
    """
    if MODEL_AXIS not in mesh.axis_names:
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), state)
    rule = _RULES[model_name]
    mp = mesh.shape[MODEL_AXIS]
    matched, fell_back = [], []

    def leaf(path, x):
        spec = rule(_path_names(path), len(getattr(x, "shape", ())))
        if spec != P():
            matched.append(path)
        for dim, axis in enumerate(spec):
            if axis == MODEL_AXIS and x.shape[dim] % mp:
                fell_back.append(path)
                log.warning(
                    "TP: %s dim %d (size %d) not divisible by "
                    "model_parallel=%d; replicating this leaf",
                    jax.tree_util.keystr(path), dim, x.shape[dim], mp)
                spec = P()
                break
        return NamedSharding(mesh, spec)

    out = jax.tree_util.tree_map_with_path(leaf, state)
    if not matched:
        raise ValueError(
            f"model_parallel={mp} requested but no parameter of model "
            f"{model_name!r} matched the TP placement rules — the run "
            "would silently execute as pure DP (were layers renamed?)")
    if len(fell_back) == len(matched):
        raise ValueError(
            f"model_parallel={mp} requested but every matched parameter "
            f"fell back to replicated (no sharded dim divisible by {mp}) "
            "— the run would silently execute as pure DP")
    return out
