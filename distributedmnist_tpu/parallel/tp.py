"""Optional tensor parallelism over a 2-D ('data', 'model') mesh.

The reference has data parallelism only (SURVEY.md §2: "DP — the only
one"), so this is a beyond-parity capability, not a port: it exists to show
the mesh design generalizes past DP the TPU way. There is NO new step
function — the same jitted train step runs unchanged; tensor parallelism is
purely a change of parameter PLACEMENT (Megatron-style paired specs below),
and XLA's sharding propagation inserts the column/row-parallel collectives.

Pairing (for each dense pair A @ B):
  first kernel  P(None, 'model')   column-parallel: activations sharded
  its bias      P('model')
  second kernel P('model', None)   row-parallel: psum on the way out
Conv kernels and everything else stay replicated — at LeNet scale convs
have no use for TP; the dense tail is where the parameters are.

Optimizer state (adam mu/nu) mirrors the params tree, and the name-based
rules match on path components, so mu/nu leaves pick up the identical specs
for free.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"


def _mlp_rule(names: set, ndim: int) -> P:
    # XLA path: params['hidden']['kernel'|'bias']; Pallas path names them
    # hidden_kernel / hidden_bias at the top level.
    if "hidden" in names or "hidden_kernel" in names or "hidden_bias" in names:
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    if "logits" in names and ndim == 2:
        return P(MODEL_AXIS, None)
    return P()


def _lenet_rule(names: set, ndim: int) -> P:
    if "fc1" in names:
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    if "fc2" in names and ndim == 2:
        return P(MODEL_AXIS, None)
    return P()


_RULES = {"mlp": _mlp_rule, "lenet": _lenet_rule}


def _path_names(path) -> set:
    names = set()
    for p in path:
        for attr in ("key", "name"):
            v = getattr(p, attr, None)
            if isinstance(v, str):
                names.add(v)
    return names


def state_shardings(state: Any, mesh: Mesh, model_name: str):
    """NamedSharding pytree for a TrainState under the given mesh.

    1-D mesh (no 'model' axis): everything replicated — the DP baseline.
    2-D mesh: the model's rules decide; any leaf whose sharded dim would
    not divide evenly falls back to replicated.
    """
    if MODEL_AXIS not in mesh.axis_names:
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), state)
    rule = _RULES[model_name]
    mp = mesh.shape[MODEL_AXIS]

    def leaf(path, x):
        spec = rule(_path_names(path), len(getattr(x, "shape", ())))
        for dim, axis in enumerate(spec):
            if axis == MODEL_AXIS and x.shape[dim] % mp:
                spec = P()  # not divisible: replicate rather than fail
                break
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf, state)
