from distributedmnist_tpu.parallel.mesh import (  # noqa: F401
    get_devices,
    make_mesh,
    replicated,
    batch_sharded,
)
from distributedmnist_tpu.parallel import distributed  # noqa: F401
from distributedmnist_tpu.parallel import tp  # noqa: F401
