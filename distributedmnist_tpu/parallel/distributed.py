"""Multi-host bring-up — TPU-native replacement for the reference's NCCL
process-group init / rendezvous [BASELINE.json configs 3-5; SURVEY.md §2
rows 8-9].

Single host needs nothing: one process sees all local chips and XLA's
collectives ride ICI. Multi-host (config 5: "multi-host v4-32 data-parallel
LeNet-5") uses `jax.distributed.initialize` for the DCN rendezvous — the
equivalent of the reference's NCCL bootstrap, but after it everything is
still ONE logical program: a jitted step over a global mesh whose psum XLA
partitions over ICI+DCN.

Per-process data: each process loads/generates the full (tiny) dataset and
the full global index array, then `global_batch_indices` assembles a global
jax.Array from each process's addressable slice via
`jax.make_array_from_process_local_data` — the replacement for the
reference's shard-by-rank DataLoader at multi-host scale.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedmnist_tpu.parallel.mesh import DATA_AXIS


def maybe_initialize(coordinator_address: Optional[str],
                     num_processes: Optional[int],
                     process_id: Optional[int]) -> bool:
    """Rendezvous with the other hosts iff multi-host flags are present.

    Returns True when running multi-host. Idempotent-safe for tests: raises
    cleanly if jax.distributed was already initialized.
    """
    if coordinator_address is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_batch_indices(idx: np.ndarray, mesh: Mesh) -> jax.Array:
    """Build the sharded global index array for one step.

    Single-process: a plain device_put with the P('data') layout. Multi-
    process: every process computed the same global `idx` (seeded stream);
    each contributes its process-local slice and jax assembles the global
    array without any cross-host data movement.
    """
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    if jax.process_count() == 1:
        return jax.device_put(idx, sharding)
    return jax.make_array_from_process_local_data(
        sharding, _local_slice(idx, sharding), global_shape=idx.shape)


def _local_slice(idx: np.ndarray, sharding: NamedSharding) -> np.ndarray:
    """The rows of the global array this process's devices own."""
    local_idx = [
        s for d, s in sharding.addressable_devices_indices_map(idx.shape).items()
    ]
    # All addressable shards of a 1-D P('data') layout form one contiguous
    # range per process; take the union of row slices.
    starts = [s[0].start or 0 for s in local_idx]
    stops = [s[0].stop if s[0].stop is not None else idx.shape[0]
             for s in local_idx]
    return idx[min(starts):max(stops)]
