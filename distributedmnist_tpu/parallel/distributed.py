"""Multi-host bring-up — TPU-native replacement for the reference's NCCL
process-group init / rendezvous [BASELINE.json configs 3-5; SURVEY.md §2
rows 8-9].

Single host needs nothing: one process sees all local chips and XLA's
collectives ride ICI. Multi-host (config 5: "multi-host v4-32 data-parallel
LeNet-5") uses `jax.distributed.initialize` for the DCN rendezvous — the
equivalent of the reference's NCCL bootstrap, but after it everything is
still ONE logical program: a jitted step over a global mesh whose psum XLA
partitions over ICI+DCN.

Per-process data: each process loads/generates the full (tiny) dataset and
the full global index array; `put_global` then builds a global jax.Array
via `jax.make_array_from_callback`, with each process contributing only the
blocks its addressable devices own — the replacement for the reference's
shard-by-rank DataLoader at multi-host scale.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P



def _is_initialized() -> bool:
    """jax.distributed.is_initialized, tolerating jax versions that
    predate the public accessor (e.g. 0.4.37): fall back to the private
    global_state's live client, defaulting to 'not initialized' if that
    moves too — initialize() would then raise on a genuine double-init,
    which is still a clear error rather than silent reuse."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except ImportError:
        return False


def maybe_initialize(coordinator_address: Optional[str],
                     num_processes: Optional[int],
                     process_id: Optional[int]) -> bool:
    """Rendezvous with the other hosts iff multi-host flags are present.

    Returns True when running multi-host. A second fit() in an
    already-initialized process (e.g. back-to-back workloads in one
    worker) reuses the live rendezvous ONLY when the requested topology
    matches it (num_processes/process_id); a mismatch raises ValueError —
    silently reusing a different topology would be a bug, not a
    reconnect. A differing coordinator string merely warns (jax may
    normalize the address, and it is only readable from private state).
    """
    if coordinator_address is None:
        return False
    if not _is_initialized():
        # Multi-process runs on the CPU backend (the localhost test/gate
        # topology) need an explicit cross-process collectives
        # implementation on jax versions whose default CPU client is
        # single-process-only ("Multiprocess computations aren't
        # implemented on the CPU backend"). The option only affects CPU
        # client creation, so it is set unconditionally — probing the
        # backend here would force backend init BEFORE the rendezvous,
        # which must come first. No-op where gloo is already the
        # default; skipped where the option no longer exists.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (ValueError, AttributeError):  # option absent/renamed
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    # Reusing the live rendezvous must not mask a config mismatch: a
    # second fit() asking for a DIFFERENT topology is a bug, not a
    # reconnect. The integer topology is checked against the PUBLIC
    # post-init accessors (they reflect the live rendezvous); a mismatch
    # is unambiguous — raise. The coordinator string may be normalized by
    # jax (host resolution) and is only readable from private state, so a
    # differing string merely warns, best-effort.
    for name, want, have in (
            ("num_processes", num_processes, jax.process_count()),
            ("process_id", process_id, jax.process_index())):
        if want is not None and want != have:
            raise ValueError(
                f"jax.distributed already initialized with {name}={have}; "
                f"this run asked for {name}={want} — refusing to silently "
                "reuse a rendezvous with a different topology")
    try:
        from jax._src.distributed import global_state as _gs
        have_addr = getattr(_gs, "coordinator_address", None)
    except ImportError:  # private module moved; skip the warning only
        have_addr = None
    if have_addr is not None and have_addr != coordinator_address:
        import logging
        logging.getLogger("distributedmnist_tpu").warning(
            "reusing live jax.distributed rendezvous at %s (this run "
            "asked for %s)", have_addr, coordinator_address)
    return True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def put_global(arr: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """Place a host array with an arbitrary sharding, single- OR
    multi-process.

    Every process holds the full (tiny — MNIST-scale) host array; each
    contributes exactly the blocks its addressable devices own, so no
    cross-host data movement happens. Single-process this is equivalent to
    device_put but goes through the same code path, keeping the multi-host
    seam permanently exercised (SURVEY.md §7.3: multi-host correctness must
    live behind clean, testable seams).
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def put_replicated(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    return put_global(arr, NamedSharding(mesh, P()))


_AGREE_FNS: dict = {}


def agree_max(value: int, mesh: Mesh) -> int:
    """Cross-process max of a per-process int — the trainer's preemption
    flag agreement. Implemented as a device-per-slot global array (each
    process contributes its own value via put_global) reduced by a jitted
    max over the CALLER'S live mesh, instead of
    multihost_utils.process_allgather: that helper builds a fresh global
    mesh per call, which segfaults on jax 0.4.37's multi-process CPU
    (gloo) backend after an orbax restore (observed in the dp:2proc gate
    leg) — while collectives over the existing mesh, the exact machinery
    every training step already exercises, are solid. Single-process:
    the value itself."""
    if jax.process_count() == 1:
        return int(value)
    import jax.numpy as jnp

    n = int(np.prod(mesh.devices.shape))
    # one slot per device, dim 0 sharded over EVERY mesh axis, so each
    # process's addressable shards carry exactly its local value
    spec = NamedSharding(mesh, P(mesh.axis_names))
    garr = put_global(np.full((n,), value, np.int32), spec)
    fn = _AGREE_FNS.get(mesh)
    if fn is None:
        fn = jax.jit(jnp.max,
                     out_shardings=NamedSharding(mesh, P()))
        _AGREE_FNS[mesh] = fn
    return int(fn(garr))


