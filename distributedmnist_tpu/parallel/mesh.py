"""Device mesh construction — the TPU-native replacement for the reference's
NCCL process group [BASELINE.json north_star: "the per-step NCCL gradient
allreduce maps to lax.psum over a named ICI device mesh"].

The mesh has a single named axis 'data' because data parallelism is the
reference's only parallelism strategy (SURVEY.md §2 parallelism table). All
sharding in the framework is expressed against this axis; collectives over
it ride ICI within a host and DCN across hosts, inserted by XLA.

Device selection honors the reference's `--device` flag [north_star: "the
existing train.py entrypoint gains a --device=tpu flag"]: 'cpu' targets the
always-present CPU backend (with XLA_FLAGS=--xla_force_host_platform_
device_count=N giving N virtual devices — the multi-chip test strategy,
SURVEY.md §3.4), 'tpu' requires real TPU chips, 'auto' takes the default
backend.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def get_devices(device: str = "auto",
                num_devices: Optional[int] = None) -> list:
    if device == "auto":
        devs = jax.devices()
    elif device == "cpu":
        devs = jax.devices("cpu")
    elif device == "tpu":
        devs = [d for d in jax.devices() if d.platform == "tpu"]
        if not devs:
            raise RuntimeError("--device=tpu requested but no TPU visible")
    else:
        raise ValueError(f"unknown device {device!r}")
    if num_devices is not None:
        if num_devices > len(devs):
            raise RuntimeError(
                f"requested {num_devices} devices, only {len(devs)} visible "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"with --device=cpu for virtual devices)")
        devs = devs[:num_devices]
    return devs


def make_mesh(devices: Sequence, model_parallel: int = 1) -> Mesh:
    """1-D ('data',) mesh by default — DP is the reference's only strategy.
    model_parallel > 1 folds the devices into a 2-D ('data', 'model') mesh
    for the optional tensor-parallel placement (parallel/tp.py)."""
    devices = np.asarray(devices)
    if model_parallel <= 1:
        return Mesh(devices, (DATA_AXIS,))
    if devices.size % model_parallel:
        raise ValueError(
            f"{devices.size} devices not divisible by "
            f"model_parallel={model_parallel}")
    return Mesh(devices.reshape(-1, model_parallel), (DATA_AXIS, "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Leading axis split over 'data', remaining axes replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))
