"""Multi-process smoke worker for the driver's dryrun gate
(`__graft_entry__.dryrun_multichip`, leg `dp:2proc`).

BASELINE.json config 5 is multi-HOST; a single-process mesh — however many
virtual devices it has — never exercises the `jax.distributed` rendezvous,
the cross-process psum, or orbax's cross-process save coordination. This
worker is one process of an N-process localhost run: it joins the
rendezvous, owns `--devices-per-proc` virtual CPU devices of the global
mesh, runs a short data-parallel fit (with checkpoint save/restore when
`--ckpt-dir` is given), and prints one `MHSMOKE {json}` line the gate
asserts on. Run as `python -m distributedmnist_tpu.parallel.mh_smoke`.

Kept deliberately self-contained (argparse + env setup + one fit) so the
driver gate has no dependency on the test tree; the richer assertions
(gather locality, preemption agreement) live in tests/multihost_worker.py.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("process_id", type=int)
    p.add_argument("num_processes", type=int)
    p.add_argument("port")
    p.add_argument("--devices-per-proc", type=int, default=4)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--steps", type=int, default=6)
    args = p.parse_args()

    # Env must be fixed BEFORE jax's first backend init: CPU-only (no TPU
    # relay dial from gate workers) and exactly devices-per-proc virtual
    # devices, replacing any count inherited from the parent.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags
        + f" --xla_force_host_platform_device_count={args.devices_per_proc}")

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.data import synthetic_mnist

    data = synthetic_mnist(seed=3, train_n=1024, test_n=256)
    cfg = Config(model="mlp", optimizer="sgd", learning_rate=0.05,
                 device="cpu", synthetic=True, batch_size=64,
                 steps=args.steps, eval_every=args.steps, log_every=0,
                 target_accuracy=None,
                 coordinator_address=f"localhost:{args.port}",
                 num_processes=args.num_processes,
                 process_id=args.process_id,
                 checkpoint_dir=args.ckpt_dir,
                 checkpoint_every=max(1, args.steps // 2))
    out = trainer.fit(cfg, data=data)
    print("MHSMOKE " + json.dumps({
        "process_id": args.process_id,
        "multihost": out["multihost"],
        "n_processes": out["n_processes"],
        "n_chips": out["n_chips"],
        "steps": out["steps"],
        "restored": out["restored"],
        "accuracy": out["test_accuracy"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
