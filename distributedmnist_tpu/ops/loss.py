"""Loss and eval math — the reference's cross-entropy / test-accuracy path
[BASELINE.json metric: "wall-clock to 99% test accuracy"].

Numerics live in float32 regardless of compute dtype: logits produced in
bfloat16 are upcast before the log-softmax so the loss/accuracy thresholds
(the 99% target) are not perturbed by low-precision reductions.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels, in f32."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels).mean()


def accuracy_count(logits: jnp.ndarray, labels: jnp.ndarray,
                   valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Number of correct predictions (int32). `valid` is an optional bool
    mask used by the padded-tail eval batches (data/loader.eval_batches)."""
    hit = (jnp.argmax(logits, axis=-1) == labels)
    if valid is not None:
        hit = jnp.logical_and(hit, valid)
    return hit.sum(dtype=jnp.int32)
