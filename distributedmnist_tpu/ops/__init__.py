from distributedmnist_tpu.ops.loss import (  # noqa: F401
    cross_entropy,
    accuracy_count,
)
from distributedmnist_tpu.ops import fused  # noqa: F401
