"""Pallas fused dense+bias+relu — the framework's exemplar custom TPU kernel.

One MXU matmul with the bias-add and relu fused into the kernel epilogue, so
the activation never round-trips HBM between the matmul and the nonlinearity.
XLA's fusion usually achieves the same; this kernel pins it deterministically
and demonstrates the Pallas path the framework uses for hot ops
(/opt/skills/guides/pallas_guide.md playbook: block over M x N, keep the
reduction dim whole in VMEM, accumulate in f32 via preferred_element_type).

The kernel is forward-only; training routes gradients through a custom VJP
whose backward is plain XLA (dx = g@W.T etc.) — the standard split for
epilogue-fused kernels.

Mode resolution happens against the platform of the mesh the step actually
runs on (NOT jax.default_backend(), which may differ under --device=cpu on
a TPU host): `resolve(mode, platform)` returns the concrete kernel choice,
and on non-TPU platforms the Pallas path runs in interpret mode so the same
code is exercised by CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Concrete kernel modes after resolution.
PALLAS = "pallas"            # compiled Pallas kernel (TPU)
PALLAS_INTERPRET = "pallas-interpret"   # Pallas in interpret mode (tests)
XLA = "xla"                  # plain jnp; XLA fuses


def resolve(mode: str, platform: str | None = None) -> str:
    """Map a user-facing mode {auto, pallas, xla} to a concrete kernel
    choice for the platform the computation will run on."""
    platform = platform or jax.default_backend()
    if mode == "xla":
        return XLA
    if mode == "pallas":
        return PALLAS if platform == "tpu" else PALLAS_INTERPRET
    if mode == "auto":
        return PALLAS if platform == "tpu" else XLA
    if mode in (PALLAS, PALLAS_INTERPRET):
        return mode
    raise ValueError(f"unknown fused-kernel mode {mode!r}")


def _dense_relu_kernel(x_ref, w_ref, b_ref, o_ref):
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.float32)
    acc = acc + b_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(acc, 0.0).astype(o_ref.dtype)


def _dense_relu_fwd_pallas(x: jax.Array, w: jax.Array, b: jax.Array,
                           interpret: bool) -> jax.Array:
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = 128 if m >= 128 else m          # MXU-friendly row tile
    bn = 128 if n >= 128 else n
    pad_m = (-m) % bm
    pad_n = (-n) % bn
    if pad_m:
        x = jnp.pad(x, ((0, pad_m), (0, 0)))
    if pad_n:
        w = jnp.pad(w, ((0, 0), (0, pad_n)))
        b = jnp.pad(b, (0, pad_n))
    mp, np_ = m + pad_m, n + pad_n
    b2 = b.reshape(1, np_)
    out = pl.pallas_call(
        _dense_relu_kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=interpret,
    )(x, w, b2)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def dense_relu(x: jax.Array, w: jax.Array, b: jax.Array,
               interpret: bool = False) -> jax.Array:
    """relu(x @ w + b) with the forward fused in a single Pallas kernel."""
    return _dense_relu_fwd_pallas(x, w, b, interpret)


def _fwd(x, w, b, interpret):
    y = _dense_relu_fwd_pallas(x, w, b, interpret)
    return y, (x, w, y)


def _bwd(interpret, res, g):
    x, w, y = res
    g = jnp.where(y > 0, g, 0).astype(jnp.float32)
    dx = (g @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = (x.astype(jnp.float32).T @ g).astype(w.dtype)
    db = g.sum(axis=0).astype(w.dtype)
    return dx, dw, db


dense_relu.defvjp(_fwd, _bwd)


@jax.jit
def dense_relu_reference(x, w, b):
    """XLA reference implementation — the equivalence oracle in tests."""
    return jnp.maximum(x @ w + b, 0.0)
