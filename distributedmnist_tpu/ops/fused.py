"""Pallas fused dense+bias+relu — the framework's exemplar custom TPU kernel.

One MXU matmul with the bias-add and relu fused into the kernel epilogue, so
the activation never round-trips HBM between the matmul and the nonlinearity.
XLA's fusion usually achieves the same; this kernel pins it deterministically
and demonstrates the Pallas path the framework uses for hot ops
(/opt/skills/guides/pallas_guide.md playbook: block over M x N, keep the
reduction dim whole in VMEM, accumulate in f32 via preferred_element_type).

The kernel is forward-only; training routes gradients through a custom VJP
whose backward is plain XLA (dx = g@W.T etc.) — the standard split for
epilogue-fused kernels.

Mode resolution happens against the platform of the mesh the step actually
runs on (NOT jax.default_backend(), which may differ under --device=cpu on
a TPU host): `resolve(mode, platform)` returns the concrete kernel choice,
and on non-TPU platforms the Pallas path runs in interpret mode so the same
code is exercised by CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Concrete kernel modes after resolution.
PALLAS = "pallas"            # compiled Pallas kernel (TPU)
PALLAS_INTERPRET = "pallas-interpret"   # Pallas in interpret mode (tests)
XLA = "xla"                  # plain jnp; XLA fuses


def resolve(mode: str, platform: str | None = None) -> str:
    """Map a user-facing mode {auto, pallas, xla} to a concrete kernel
    choice for the platform the computation will run on."""
    platform = platform or jax.default_backend()
    if mode == "xla":
        return XLA
    if mode == "pallas":
        return PALLAS if platform == "tpu" else PALLAS_INTERPRET
    if mode == "auto":
        return PALLAS if platform == "tpu" else XLA
    if mode in (PALLAS, PALLAS_INTERPRET):
        return mode
    raise ValueError(f"unknown fused-kernel mode {mode!r}")


def _dense_relu_kernel(x_ref, w_ref, b_ref, o_ref):
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.float32)
    acc = acc + b_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(acc, 0.0).astype(o_ref.dtype)


def _tiled_dense_call(kernel, x: jax.Array, w: jax.Array,
                      channel_rows: list, out_dtype,
                      interpret: bool) -> jax.Array:
    """The one M x N tiling scaffold every fused dense kernel runs on
    (pallas_guide.md playbook: block over M x N with MXU-friendly
    tiles, keep the reduction dim whole in VMEM): pad (m, k) x and
    (k, n) w up to the tile grid, pad each per-output-channel vector in
    `channel_rows` (bias, dequant scales, ...) along n and hand them to
    `kernel` as (1, bn) blocks, slice the padding back off the (m, n)
    result. One definition, so a tiling-rule change can never diverge
    between the training kernel and the inference epilogues."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = 128 if m >= 128 else m          # MXU-friendly row tile
    bn = 128 if n >= 128 else n
    pad_m = (-m) % bm
    pad_n = (-n) % bn
    if pad_m:
        x = jnp.pad(x, ((0, pad_m), (0, 0)))
    if pad_n:
        w = jnp.pad(w, ((0, 0), (0, pad_n)))
        channel_rows = [jnp.pad(r, (0, pad_n)) for r in channel_rows]
    mp, np_ = m + pad_m, n + pad_n
    out = pl.pallas_call(
        kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ] + [pl.BlockSpec((1, bn), lambda i, j: (0, j))
             for _ in channel_rows],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        interpret=interpret,
    )(x, w, *[r.reshape(1, np_) for r in channel_rows])
    return out[:m, :n]


def _dense_relu_fwd_pallas(x: jax.Array, w: jax.Array, b: jax.Array,
                           interpret: bool) -> jax.Array:
    return _tiled_dense_call(_dense_relu_kernel, x, w, [b], x.dtype,
                             interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def dense_relu(x: jax.Array, w: jax.Array, b: jax.Array,
               interpret: bool = False) -> jax.Array:
    """relu(x @ w + b) with the forward fused in a single Pallas kernel."""
    return _dense_relu_fwd_pallas(x, w, b, interpret)


def _fwd(x, w, b, interpret):
    y = _dense_relu_fwd_pallas(x, w, b, interpret)
    return y, (x, w, y)


def _bwd(interpret, res, g):
    x, w, y = res
    g = jnp.where(y > 0, g, 0).astype(jnp.float32)
    dx = (g @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = (x.astype(jnp.float32).T @ g).astype(w.dtype)
    db = g.sum(axis=0).astype(w.dtype)
    return dx, dw, db


dense_relu.defvjp(_fwd, _bwd)


@jax.jit
def dense_relu_reference(x, w, b):
    """XLA reference implementation — the equivalence oracle in tests."""
    return jnp.maximum(x @ w + b, 0.0)


# -- forward-only inference epilogues (serve/quantize.py fast path) --------
#
# The serving engines never differentiate, so their fused ops skip the
# custom-VJP wrapper entirely: dense_relu_inference is the same fused
# dense+bias+relu as dense_relu but dispatchable on a RESOLVED mode, and
# quant_dense is its int8 weight-quantized sibling — the scaled
# int8 x int8 -> int32 matmul with the f32 dequant (+bias, optional relu)
# folded into the kernel epilogue (pallas_guide.md quantization pattern:
# int32 accumulate on the MXU, per-output-channel scales applied once on
# the way out). On non-TPU platforms the Pallas paths run in interpret
# mode — the equivalence tests' route — while production CPU serving uses
# the XLA mode (serve/quantize.py dequantizes at build there; interpret
# mode is a correctness vehicle, not a fast path).


def dense_relu_inference(x: jax.Array, w: jax.Array, b: jax.Array,
                         mode: str = XLA) -> jax.Array:
    """relu(x @ w + b), forward-only, on a resolved kernel mode. The
    XLA arm IS dense_relu_reference — one definition, so the oracle the
    equivalence tests compare against can never drift from the
    production route."""
    if mode == XLA:
        return dense_relu_reference(x, w, b)
    if mode in (PALLAS, PALLAS_INTERPRET):
        return _dense_relu_fwd_pallas(x, w, b, mode == PALLAS_INTERPRET)
    raise ValueError(f"unresolved fused-kernel mode {mode!r}")


def _quant_dense_kernel(relu, x_ref, w_ref, s_ref, b_ref, o_ref):
    # int8 x int8 on the MXU accumulates in int32; the dequant epilogue
    # (per-output-channel scale, f32 bias, optional relu) runs on the
    # VPU before the tile ever leaves VMEM.
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * s_ref[...] + b_ref[...]
    if relu:
        out = jnp.maximum(out, 0.0)
    o_ref[...] = out


def quant_dense(x_q: jax.Array, w_q: jax.Array, scale: jax.Array,
                b: jax.Array, relu: bool = True,
                mode: str = XLA) -> jax.Array:
    """Weight-quantized dense layer: (x_q @ w_q) * scale + b, optionally
    relu'd, returning float32.

    x_q (m, k) int8, w_q (k, n) int8, scale (n,) float32 — the COMBINED
    dequant factor (weight scale x activation scale; the caller folds its
    activation quantization step in), b (n,) float32.
    """
    if x_q.dtype != jnp.int8 or w_q.dtype != jnp.int8:
        raise TypeError(
            f"quant_dense wants int8 operands, got {x_q.dtype} @ "
            f"{w_q.dtype}")
    if mode == XLA:
        return quant_dense_reference(x_q, w_q, scale, b, relu=relu)
    if mode not in (PALLAS, PALLAS_INTERPRET):
        raise ValueError(f"unresolved fused-kernel mode {mode!r}")
    return _tiled_dense_call(
        functools.partial(_quant_dense_kernel, relu), x_q, w_q,
        [jnp.asarray(scale, jnp.float32), jnp.asarray(b, jnp.float32)],
        jnp.float32, mode == PALLAS_INTERPRET)


def quant_dense_reference(x_q, w_q, scale, b, relu: bool = True):
    """Plain-jnp oracle for quant_dense — the equivalence tests compare
    the Pallas-interpret kernel against THIS, and it is also exactly the
    XLA-mode implementation (one definition, asserted equal)."""
    acc = jax.lax.dot_general(x_q, w_q, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * scale + b
    return jnp.maximum(out, 0.0) if relu else out


# -- whole-net inference megakernel (ISSUE 14) -----------------------------
#
# The per-layer epilogues above still dispatch one fused call PER LAYER;
# at single-request batch sizes the dispatch overhead of the layer chain
# dominates the arithmetic. The megakernel runs the ENTIRE MLP forward —
# relu(x @ w1 + b1) @ w2 + b2 — as ONE Pallas call: both weight
# matrices live whole in VMEM (784x128 + 128x10 floats, ~400 KB), the
# hidden activation never leaves VMEM, and the grid blocks over batch
# rows only (pallas_guide.md playbook: small N padded up to one lane
# tile, sliced off after). Forward-only like every inference epilogue;
# serve/quantize.py serves it as the parity-gated `megakernel` variant,
# interpret mode on CPU tests exactly like the int8 kernel (production
# CPU serving takes the XLA oracle route — one fused jnp expression XLA
# fuses well; the compiled-Pallas arm is the TPU route).


def _mlp_mega_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref):
    h = jnp.dot(x_ref[...], w1_ref[...],
                preferred_element_type=jnp.float32)
    h = jnp.maximum(h + b1_ref[...].astype(jnp.float32), 0.0)
    o = jnp.dot(h, w2_ref[...].astype(jnp.float32),
                preferred_element_type=jnp.float32)
    o_ref[...] = (o + b2_ref[...].astype(jnp.float32)).astype(
        o_ref.dtype)


def mlp_megakernel(x: jax.Array, w1: jax.Array, b1: jax.Array,
                   w2: jax.Array, b2: jax.Array,
                   mode: str = XLA) -> jax.Array:
    """relu(x @ w1 + b1) @ w2 + b2 in one fused call, on a resolved
    kernel mode. The XLA arm IS mlp_megakernel_reference — one
    definition, so the parity oracle can never drift from the
    production route."""
    if mode == XLA:
        return mlp_megakernel_reference(x, w1, b1, w2, b2)
    if mode not in (PALLAS, PALLAS_INTERPRET):
        raise ValueError(f"unresolved fused-kernel mode {mode!r}")
    m, k = x.shape
    k2, hdim = w1.shape
    assert k == k2, (x.shape, w1.shape)
    h2, n = w2.shape
    assert hdim == h2, (w1.shape, w2.shape)
    bm = 128 if m >= 128 else m          # batch-row tile
    # the (tiny) logits dim ALWAYS pads up to one full lane tile so
    # the second matmul's output block is MXU-shaped (10 -> 128);
    # sliced off below — unconditional, so the interpret-mode tests
    # exercise the same padded graph the TPU route compiles
    bn = 128
    pad_m = (-m) % bm
    pad_n = (-n) % bn
    if pad_m:
        x = jnp.pad(x, ((0, pad_m), (0, 0)))
    if pad_n:
        w2 = jnp.pad(w2, ((0, 0), (0, pad_n)))
        b2 = jnp.pad(b2, (0, pad_n))
    mp, np_ = m + pad_m, n + pad_n
    out = pl.pallas_call(
        _mlp_mega_kernel,
        grid=(mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, hdim), lambda i: (0, 0)),
            pl.BlockSpec((1, hdim), lambda i: (0, 0)),
            pl.BlockSpec((hdim, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, np_), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=mode == PALLAS_INTERPRET,
    )(x, w1, b1.reshape(1, hdim), w2, b2.reshape(1, np_))
    return out[:m, :n]


@jax.jit
def mlp_megakernel_reference(x, w1, b1, w2, b2):
    """XLA oracle for the megakernel — the equivalence tests' basis and
    exactly the XLA-mode implementation (one definition)."""
    return jnp.maximum(x @ w1 + b1, 0.0) @ w2 + b2
