"""Patch-matmul (im2col) convolution + reshape pooling — the TPU conv path.

A 5x5 conv on a 28x28 MNIST image is a tiny convolution, and the MXU is a
matmul engine: the TPU-native formulation extracts the kh*kw shifted slices
of the input once (static slices — XLA folds them into cheap pads/copies)
and computes one (B*oh*ow, kh*kw*C) x (kh*kw*C, F) matmul. Forward AND
backward then consist purely of matmuls and slice/pad ops — no
conv_general_dilated anywhere — which keeps the whole training step on the
MXU fast path and sidesteps XLA conv-backward lowering entirely (on this
host's experimental 'axon' TPU platform, compiling any conv backward wedges
the compiler indefinitely; measured: a single nn.Conv grad never finishes,
the patch-matmul grad compiles in ~3s).

avg_pool 2x2/2 is a reshape + mean over the two window axes — its backward
is a broadcast, again avoiding reduce_window's backward lowering.

Numerics match lax convs to float tolerance (accumulation order differs);
equivalence is pinned by tests/test_conv.py. Parameter pytrees are
IDENTICAL to flax nn.Conv ({kernel (kh,kw,C,F), bias (F,)}), so checkpoints
written with either conv implementation restore into the other — the
implementation choice is a per-run compute detail, not a model change.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def im2col_conv(x: jnp.ndarray, kernel: jnp.ndarray, bias: jnp.ndarray,
                padding: str = "VALID") -> jnp.ndarray:
    """2-D convolution (NHWC, stride 1) as one patch matmul.

    x (B,H,W,C), kernel (kh,kw,C,F), bias (F,). padding in {VALID, SAME}
    (SAME requires odd kernel dims, which LeNet's 5x5 satisfies).
    """
    kh, kw, cin, feat = kernel.shape
    if padding == "SAME":
        x = jnp.pad(x, ((0, 0), (kh // 2, kh // 2),
                        (kw // 2, kw // 2), (0, 0)))
    elif padding != "VALID":
        raise ValueError(f"unsupported padding {padding!r}")
    b, h, w, c = x.shape
    assert c == cin, (x.shape, kernel.shape)
    oh, ow = h - kh + 1, w - kw + 1
    # (B,oh,ow,kh*kw,C): kh*kw static shifted views; XLA lowers these to
    # slices whose gradients are pads — no gather/scatter involved.
    patches = jnp.stack([x[:, i:i + oh, j:j + ow, :]
                         for i in range(kh) for j in range(kw)], axis=3)
    patches = patches.reshape(b, oh, ow, kh * kw * c)
    return patches @ kernel.reshape(kh * kw * cin, feat) + bias


def avg_pool2(x: jnp.ndarray) -> jnp.ndarray:
    """2x2 stride-2 average pool via reshape+mean (even H and W)."""
    b, h, w, c = x.shape
    return x.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))


class PatchConv(nn.Module):
    """Drop-in for nn.Conv(features, kernel_size, padding) with the
    patch-matmul implementation; parameter names/shapes/init identical to
    nn.Conv so the two are checkpoint-compatible."""

    features: int
    kernel_size: tuple[int, int]
    padding: str = "VALID"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        return im2col_conv(x.astype(self.dtype), kernel.astype(self.dtype),
                           bias.astype(self.dtype), padding=self.padding)
