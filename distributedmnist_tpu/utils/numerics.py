"""Small shared numeric helpers."""

from __future__ import annotations


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of `multiple` that is >= x."""
    return ((x + multiple - 1) // multiple) * multiple
