"""Small shared numeric helpers, including the logit-parity comparison
the serving stack's accuracy gates run on.

The parity helpers compare a CANDIDATE forward's logits against a
REFERENCE forward's on the same batch — the shadow-router comparison
(serve/router.py) and the registry's dtype-variant parity gate
(serve/registry.py) both speak this vocabulary:

- **argmax agreement**: the fraction of rows whose predicted class is
  unchanged — the deployment-relevant signal (a served classifier's
  OUTPUT is the argmax).
- **max relative logit diff**: the worst absolute logit gap, normalized
  by the reference batch's own logit magnitude. Absolute thresholds
  don't transfer between a fresh-init model (logit spread ~0.05) and a
  trained one (spread ~10), but low-precision arithmetic error scales
  WITH the logits, so the relative form is the stable gate (PARITY.md
  "Serving parity gate" documents the thresholds and their headroom).
"""

from __future__ import annotations


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of `multiple` that is >= x."""
    return ((x + multiple - 1) // multiple) * multiple


def argmax_agreement(ref, cand) -> float:
    """Fraction of rows where argmax(ref) == argmax(cand); both (n, k)."""
    import numpy as np

    ref = np.asarray(ref)
    cand = np.asarray(cand)
    if ref.shape != cand.shape:
        raise ValueError(
            f"shape mismatch: reference {ref.shape} vs candidate "
            f"{cand.shape}")
    return float(np.mean(ref.argmax(-1) == cand.argmax(-1)))


def max_abs_diff(ref, cand) -> float:
    """Worst absolute elementwise gap between two logit arrays."""
    import numpy as np

    return float(np.max(np.abs(np.asarray(ref, dtype=np.float32)
                               - np.asarray(cand, dtype=np.float32))))


def logit_parity(ref, cand) -> dict:
    """The full comparison record: agreement, absolute and relative
    worst logit gaps, and the reference scale the relative form is
    normalized by."""
    import numpy as np

    ref = np.asarray(ref, dtype=np.float32)
    cand = np.asarray(cand, dtype=np.float32)
    diff = max_abs_diff(ref, cand)
    # The normalizer is the reference batch's own worst logit magnitude
    # (floored so an all-zero reference can't divide by zero): error in
    # low-precision arithmetic scales with the values themselves.
    ref_scale = max(float(np.max(np.abs(ref))), 1e-6)
    return {
        "rows": int(ref.shape[0]),
        "argmax_agreement": round(argmax_agreement(ref, cand), 6),
        "max_abs_logit_diff": round(diff, 6),
        "ref_logit_scale": round(ref_scale, 6),
        "max_rel_logit_diff": round(diff / ref_scale, 6),
    }


def parity_check(ref, cand, min_agreement: float,
                 max_rel_diff: float) -> dict:
    """logit_parity plus the pass/fail verdict against the two gate
    thresholds; `why` spells out the failing threshold(s) so a refusal's
    last_error reads as a sentence, not a number dump."""
    rep = logit_parity(ref, cand)
    reasons = []
    if rep["argmax_agreement"] < min_agreement:
        reasons.append(
            f"argmax agreement {rep['argmax_agreement']:.4f} < "
            f"{min_agreement:.4f}")
    if rep["max_rel_logit_diff"] > max_rel_diff:
        reasons.append(
            f"max relative logit diff {rep['max_rel_logit_diff']:.4f} > "
            f"{max_rel_diff:.4f}")
    rep["min_agreement"] = min_agreement
    rep["max_rel_diff"] = max_rel_diff
    rep["passed"] = not reasons
    rep["why"] = "; ".join(reasons) if reasons else None
    return rep
