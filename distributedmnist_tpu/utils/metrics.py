"""Metrics / observability — images/sec/chip, wall-clock-to-target-accuracy,
machine-readable JSON summary [BASELINE.json metric: "MNIST images/sec/chip;
wall-clock to 99% test accuracy"; SURVEY.md §2 row 11, §5].

Timing respects JAX's async dispatch: StepTimer only closes a window after
a device->host VALUE fetch of the last step's output (StepTimer.barrier) —
not block_until_ready, which on pooled/tunneled PJRT backends can report
ready long before execution completes. Measured step time is therefore true
device time + dispatch, not just host dispatch.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any, Optional

import jax

log = logging.getLogger("distributedmnist_tpu")


def percentiles(values, qs=(50, 95, 99)) -> dict:
    """{f"p{q}": value} by linear interpolation over sorted `values`
    (numpy's default quantile method). Empty input yields None per key —
    a serving window with zero completed requests must not fake a zero
    latency. Shared by serve/metrics.py and the bench's latency tables."""
    import numpy as np

    if len(values) == 0:
        return {f"p{int(q)}": None for q in qs}
    arr = np.asarray(values, dtype=np.float64)
    out = np.quantile(arr, [q / 100.0 for q in qs])
    return {f"p{int(q)}": float(v) for q, v in zip(qs, out)}


class StepTimer:
    """Throughput accounting over the hot loop, excluding compile.

    Call start() after warmup (first step compiled), lap() each step; the
    first lap after start() sets t0. images/sec/chip = images / elapsed /
    n_chips.
    """

    def __init__(self, global_batch: int, n_chips: int):
        self.global_batch = global_batch
        self.n_chips = n_chips
        self.t0: Optional[float] = None
        self.steps = 0
        self.excluded = 0.0

    @staticmethod
    def barrier(sync: Any) -> None:
        """Force completion of the computation producing `sync` via a
        device->host VALUE fetch of one leaf. On tunneled/pooled PJRT
        backends block_until_ready can return before execution actually
        completes (measured on this host's relay: a chain of scanned train
        steps 'ready' ~60x faster than its true execution time, while a
        value fetch always waits); fetching bytes cannot lie."""
        leaves = jax.tree.leaves(sync)
        if leaves:
            jax.device_get(leaves[0])

    def start(self, sync: Any = None) -> None:
        if sync is not None:
            self.barrier(sync)
        self.t0 = time.perf_counter()
        self.steps = 0
        self.excluded = 0.0

    def lap(self, n: int = 1) -> None:
        self.steps += n

    @contextlib.contextmanager
    def exclude(self):
        """Exclude a non-training span (eval, checkpoint IO) from the
        throughput window."""
        t = time.perf_counter()
        try:
            yield
        finally:
            self.excluded += time.perf_counter() - t

    def snapshot(self, sync: Any = None) -> dict:
        if sync is not None:
            self.barrier(sync)
        # No window was ever opened (e.g. an eval-only run): report a zero
        # window rather than `-excluded` (excluded spans can accrue from
        # eval even when start() never ran).
        if self.t0 is None:
            elapsed = 0.0
        else:
            elapsed = time.perf_counter() - self.t0 - self.excluded
        images = self.steps * self.global_batch
        ips = images / elapsed if elapsed > 0 else 0.0
        return {
            "elapsed_s": elapsed,
            "steps_timed": self.steps,
            "images_per_sec": ips,
            "images_per_sec_per_chip": ips / max(self.n_chips, 1),
            "step_ms": 1000.0 * elapsed / self.steps if self.steps else 0.0,
        }


class MetricsLogger:
    """Per-step scalar log + final JSON line for the driver harness.
    Cadence gating is the caller's responsibility (the trainer gates on
    block-crossing); every step()/eval() call is recorded."""

    def __init__(self):
        self.history: list[dict] = []

    def step(self, step: int, scalars: dict) -> None:
        """Record + log one step's scalars. Cadence is the caller's job
        (the trainer gates on block-crossing); calling this forces a device
        sync via float(), so don't call it every step on TPU."""
        rec = {"step": step}
        rec.update({k: float(v) for k, v in scalars.items()})
        self.history.append(rec)
        log.info("step %6d  %s", step,
                 "  ".join(f"{k}={v:.4g}" for k, v in rec.items()
                           if k != "step"))

    def eval(self, step: int, accuracy: float) -> None:
        log.info("eval step %6d  test_accuracy=%.4f", step, accuracy)
        self.history.append({"step": step, "test_accuracy": float(accuracy)})

    @staticmethod
    def summary_line(summary: dict) -> str:
        return json.dumps(summary, sort_keys=True)
