from distributedmnist_tpu.utils.compile_cache import enable_compilation_cache  # noqa: F401
from distributedmnist_tpu.utils.metrics import MetricsLogger, StepTimer  # noqa: F401
from distributedmnist_tpu.utils.numerics import round_up  # noqa: F401
