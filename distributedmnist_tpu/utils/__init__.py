"""Utils package. Submodule attributes resolve lazily (PEP 562) so that
importing `distributedmnist_tpu.utils.supervise` from a supervisor parent
process does NOT pull in jax via metrics.py — the supervisor must stay
jax-free so a wedge at backend/plugin import time is confined to the
killable worker subprocess (utils/supervise.py's contract)."""

_EXPORTS = {
    "MetricsLogger": ("distributedmnist_tpu.utils.metrics", "MetricsLogger"),
    "StepTimer": ("distributedmnist_tpu.utils.metrics", "StepTimer"),
    "round_up": ("distributedmnist_tpu.utils.numerics", "round_up"),
    "argmax_agreement": ("distributedmnist_tpu.utils.numerics",
                         "argmax_agreement"),
    "max_abs_diff": ("distributedmnist_tpu.utils.numerics",
                     "max_abs_diff"),
    "logit_parity": ("distributedmnist_tpu.utils.numerics",
                     "logit_parity"),
    "parity_check": ("distributedmnist_tpu.utils.numerics",
                     "parity_check"),
    "enable_compilation_cache": (
        "distributedmnist_tpu.utils.compile_cache",
        "enable_compilation_cache"),
    "CompileCounter": (
        "distributedmnist_tpu.utils.compile_cache", "CompileCounter"),
    "percentiles": ("distributedmnist_tpu.utils.metrics", "percentiles"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(module_name), attr)
