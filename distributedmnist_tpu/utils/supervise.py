"""Stall-watchdog process supervision for flaky pooled TPU backends.

A fresh process's first device claim through a pooled/tunneled TPU runtime
can wedge forever before any program runs (observed repeatedly on this
host's relay: the claim leg intermittently never completes while an
immediate retry in a new process succeeds). The supervisor runs the real
work in a worker subprocess, watches its stdout/stderr for activity, and
kills + retries a worker that goes silent too long. Acceptance of a
worker's output is delegated to the caller (e.g. "a parseable JSON record
with a 'metric' key"), so a crashed worker's stray output is never
forwarded as a result.

Used by bench.py (always) and train.py (--supervise).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Optional

_WORKER_ENV = "DMNIST_SUPERVISED_WORKER"


def is_worker() -> bool:
    return os.environ.get(_WORKER_ENV) == "1"


def mark(msg: str) -> None:
    """Progress marker on stderr — the supervisor's liveness signal."""
    print(f"supervise: {msg}", file=sys.stderr, flush=True)


def run_supervised(script: str, argv: list[str],
                   accept: Callable[[list[str]], Optional[str]],
                   stall_timeout: float = 300.0,
                   attempts: int = 3,
                   fallback_env: Optional[dict] = None) -> int:
    """Run `python -u script *argv` as a worker (marked via env); kill +
    retry if it produces no output for stall_timeout seconds. `accept`
    maps worker stdout lines to the result to forward (or None if they
    contain no valid result); while the worker runs it is called with
    successive chunks of NEWLY-arrived lines — not the whole buffer —
    and the most recent non-None result wins, so each line is scanned
    once per attempt. Acceptors must therefore be LINE-LOCAL (decide per
    line, like json_record_acceptor): a record straddling a poll
    boundary is split across chunks. As a safety net for acceptors that
    do need cross-line context, after the worker exits with no chunk
    result the whole buffer is re-scanned in ONE final accept() call.
    Returns the exit code; the accepted result is written to stdout.
    Never imports jax.

    If every attempt fails and `fallback_env` is given, ONE extra attempt
    runs with those env overrides (a None value UNSETS the variable) —
    e.g. forcing the CPU backend so a dead TPU runtime still yields a
    (clearly labelled) result instead of nothing."""
    total = attempts + (1 if fallback_env else 0)
    for attempt in range(1, total + 1):
        env = dict(os.environ, **{_WORKER_ENV: "1"})
        if attempt > attempts:
            mark(f"fallback attempt with env overrides {fallback_env}")
            for key, val in fallback_env.items():
                if val is None:
                    env.pop(key, None)
                else:
                    env[key] = val
        proc = subprocess.Popen(
            [sys.executable, "-u", script] + argv,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, start_new_session=True)
        last = [time.monotonic()]
        out_lines: list[str] = []

        def pump(stream, sink):
            for line in stream:
                last[0] = time.monotonic()
                sink(line)

        import threading
        threads = [
            threading.Thread(target=pump,
                             args=(proc.stdout, out_lines.append),
                             daemon=True),
            threading.Thread(target=pump,
                             args=(proc.stderr, sys.stderr.write),
                             daemon=True),
        ]
        for t in threads:
            t.start()

        # Incremental result scan: each one-second poll hands accept()
        # only the lines that arrived since the last poll and caches the
        # latest hit — re-scanning the whole buffer every poll is
        # O(lines^2) over a chatty multi-hour run (round-4 advice).
        scanned = 0
        cached = None

        def current_result():
            nonlocal scanned, cached
            new = out_lines[scanned:]
            scanned += len(new)
            if new:
                r = accept(new)
                if r is not None:
                    cached = r
            return cached

        stalled = False
        teardown_grace = min(30.0, stall_timeout)
        # Hard per-attempt ceiling: a wedged worker that emits periodic
        # chatter (retry warnings, reconnect spam) never goes quiet, so
        # silence alone cannot bound the attempt. 8x the stall timeout
        # (floor 40 min) keeps a chattering-but-wedged worker from
        # burning hours before the kill (the old 20x ratio allowed 100
        # min) while leaving room for the slowest legitimate attempt —
        # a multi-batch sweep on the CPU-fallback leg, where one window
        # takes minutes.
        deadline = time.monotonic() + max(8 * stall_timeout, 2400.0)
        while proc.poll() is None:
            quiet = time.monotonic() - last[0]
            if current_result() is not None and quiet > teardown_grace:
                # Result produced; only runtime teardown is hanging
                # (pooled-backend clients can wedge at exit too).
                break
            if quiet > stall_timeout:
                stalled = True
                stall_reason = f"no output for {stall_timeout:.0f}s"
                break
            if time.monotonic() > deadline:
                stalled = True
                stall_reason = "attempt deadline exceeded"
                break
            time.sleep(1)

        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        proc.wait()
        for t in threads:
            t.join(timeout=5)

        result = current_result()
        if result is None and out_lines:
            # Post-exit fallback: one full-buffer scan. Chunk-wise
            # scanning is line-local by contract; an acceptor needing
            # cross-line context (multi-line JSON, line pairs) would
            # miss a record split across poll boundaries — after process
            # exit the complete buffer exists, so scan it once (ADVICE
            # r5).
            result = accept(out_lines)
        if result is not None:
            sys.stdout.write(result)
            sys.stdout.flush()
            return 0
        reason = stall_reason if stalled else f"exit code {proc.returncode}"
        mark(f"worker failed ({reason}), attempt {attempt}/{total}")
    mark("all attempts failed")
    return 1


def json_record_acceptor(required_key: str):
    """accept() factory: the last stdout line that parses as a JSON object
    containing `required_key`.

    LINE-LOCAL by design — each line is judged on its own, so the
    acceptor is correct under run_supervised's chunk-wise delivery
    (accept() sees only newly-arrived lines per poll, never the whole
    buffer until the post-exit fallback). Any future acceptor that needs
    cross-line context must rely on that post-exit full-buffer scan
    instead."""
    import json

    def accept(out_lines: list[str]) -> Optional[str]:
        # The line-local contract also means every element must BE one
        # line; a caller handing in multi-line strings would defeat the
        # chunking guarantee silently.
        assert all("\n" not in line.rstrip("\n") for line in out_lines), \
            "json_record_acceptor expects one line per list element"
        for line in reversed(out_lines):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and required_key in rec:
                return line
        return None

    return accept
