"""Persistent XLA compilation cache.

First-compile latency on TPU is tens of seconds (and on this host's
tunneled 'axon' platform a fresh compile is also the phase most exposed to
runtime flakiness), so both the CLI and the benchmark enable jax's
persistent compilation cache: a compiled executable written once is reused
by every later process with the same program + platform, making retries
and repeat runs start in milliseconds instead of recompiling.

The cache lives inside the repo by default (<repo>/.jax_cache, gitignored)
so nothing outside the working tree is written; override with
DMNIST_COMPILE_CACHE=<dir> or disable with DMNIST_COMPILE_CACHE=0.
"""

from __future__ import annotations

import os


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on jax's persistent compilation cache; returns the directory
    used, or None when disabled. Safe to call more than once."""
    import jax

    env = os.environ.get("DMNIST_COMPILE_CACHE")
    if env == "0":
        return None
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cache_dir = cache_dir or env or os.path.join(repo_root, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # MNIST-scale executables are small and fast to compile on CPU; cache
    # everything that takes noticeable time, regardless of size.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir
