"""Persistent XLA compilation cache.

First-compile latency on TPU is tens of seconds (and on this host's
tunneled 'axon' platform a fresh compile is also the phase most exposed to
runtime flakiness), so both the CLI and the benchmark enable jax's
persistent compilation cache: a compiled executable written once is reused
by every later process with the same program + platform, making retries
and repeat runs start in milliseconds instead of recompiling.

The cache lives inside the repo by default (<repo>/.jax_cache, gitignored)
so nothing outside the working tree is written; override with
DMNIST_COMPILE_CACHE=<dir> or disable with DMNIST_COMPILE_CACHE=0.
"""

from __future__ import annotations

import os


class CompileCounter:
    """Monotonic count of XLA compile requests in this process, observed
    via jax.monitoring events. The serving engine's zero-recompile
    contract is asserted against this: after bucket warmup, steady-state
    inference must not grow the count (tests/test_serve_engine.py), the
    same discipline the trainer's shape-stable superstep relies on.

    jax.monitoring has no per-listener unregister, so the listener is
    installed once per process (module singleton via instance()) and
    consumers take snapshot deltas rather than owning the listener.
    """

    _instance: "CompileCounter | None" = None

    def __init__(self):
        self.count = 0

        def _on_event(event: str, **kw) -> None:
            # Both the in-memory executable path and the persistent cache
            # path emit compile-tagged events on a compile REQUEST; a jit
            # cache hit emits nothing — exactly the steady-state signal.
            if "compile" in event:
                self.count += 1

        import jax
        jax.monitoring.register_event_listener(_on_event)

    @classmethod
    def instance(cls) -> "CompileCounter":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def snapshot(self) -> int:
        return self.count


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on jax's persistent compilation cache; returns the directory
    used, or None when disabled. Safe to call more than once."""
    import jax

    env = os.environ.get("DMNIST_COMPILE_CACHE")
    if env == "0":
        return None
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cache_dir = cache_dir or env or os.path.join(repo_root, ".jax_cache")
    # Localhost multi-PROCESS runs (the gate/test topology) must not
    # share one cache directory: concurrent writers + readers of the
    # same entry files produce heap corruption inside XLA's cache
    # deserialization on jax 0.4.37 ("corrupted size vs. prev_size",
    # then a segfault in the next compile — observed in the dp:2proc
    # restore leg). Suffix a per-process subdir when a multi-process
    # rendezvous is live; real multi-host processes see different
    # filesystems anyway, so the split only costs duplicate entries.
    # Probed via distributed global_state, NOT jax.process_count(),
    # which would force backend initialization from inside a config
    # helper. Callers that want the suffix must therefore initialize
    # jax.distributed BEFORE enabling the cache (trainer.fit does).
    try:
        from jax._src.distributed import global_state
        if (global_state.client is not None
                and (global_state.num_processes or 1) > 1):
            cache_dir = os.path.join(
                cache_dir, f"proc{global_state.process_id}")
    except ImportError:  # private layout moved; keep the shared dir
        pass
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # MNIST-scale executables are small and fast to compile on CPU; cache
    # everything that takes noticeable time, regardless of size.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir
