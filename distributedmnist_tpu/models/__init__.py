from distributedmnist_tpu.models.mlp import MLP  # noqa: F401
from distributedmnist_tpu.models.lenet import LeNet5  # noqa: F401


def build(name: str, dtype=None, fused: str = "auto",
          platform: str | None = None):
    """Model factory for the two reference architectures
    [BASELINE.json configs: "2-layer MLP (784-128-10)", "LeNet-5 CNN"].

    `platform` is the platform of the devices the model will RUN on (the
    mesh's platform, not jax.default_backend()) — it resolves the 'auto'
    fused-kernel mode; None falls back to the default backend.
    """
    import jax.numpy as jnp

    from distributedmnist_tpu.ops import fused as fused_lib
    dtype = dtype or jnp.float32
    if name == "mlp":
        return MLP(dtype=dtype, fused=fused_lib.resolve(fused, platform))
    if name == "lenet":
        return LeNet5(dtype=dtype)
    raise ValueError(f"unknown model {name!r} (expected mlp|lenet)")
