from distributedmnist_tpu.models.mlp import MLP  # noqa: F401
from distributedmnist_tpu.models.lenet import LeNet5  # noqa: F401


def build(name: str, dtype=None, fused: str = "auto",
          platform: str | None = None, conv: str = "auto"):
    """Model factory for the two reference architectures
    [BASELINE.json configs: "2-layer MLP (784-128-10)", "LeNet-5 CNN"].

    `platform` is the platform of the devices the model will RUN on (the
    mesh's platform, not jax.default_backend()) — it resolves the 'auto'
    fused-kernel mode and the 'auto' conv implementation; None falls back
    to the default backend. conv in {'auto', 'im2col', 'lax'}: auto picks
    the patch-matmul convs on TPU (MXU-native; see ops/conv.py) and lax
    convs elsewhere. Both produce identical parameter pytrees.
    """
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.ops import fused as fused_lib
    dtype = dtype or jnp.float32
    if name == "mlp":
        return MLP(dtype=dtype, fused=fused_lib.resolve(fused, platform))
    if name == "lenet":
        if conv == "auto":
            conv = ("im2col"
                    if (platform or jax.default_backend()) == "tpu"
                    else "lax")
        if conv not in ("im2col", "lax"):
            raise ValueError(f"unknown conv impl {conv!r}")
        return LeNet5(dtype=dtype, conv_impl=conv)
    raise ValueError(f"unknown model {name!r} (expected mlp|lenet)")
