"""LeNet-5 CNN — BASELINE.json configs 2/4/5's model.

Classic LeCun-98 LeNet-5 adapted to 28x28 MNIST input (the original takes
32x32, so conv1 uses SAME padding): conv 5x5x6 -> avgpool 2 -> conv 5x5x16
(VALID) -> avgpool 2 -> flatten(400) -> Dense(120) -> Dense(84) -> Dense(10).
61,706 parameters (pinned by test). relu instead of tanh — the standard
modern variant, and what gets MNIST past 99% (SURVEY.md §7.3 notes LeNet-5
is the model the wall-clock-to-99% harness must default to).

TPU notes: NHWC layout throughout (TPU-native). Two checkpoint-compatible
conv implementations (identical param pytrees):
- 'im2col' (TPU default): patch-matmul convs + reshape pooling
  (ops/conv.py) — pure MXU matmuls in forward and backward.
- 'lax': flax nn.Conv / nn.avg_pool lowering to XLA conv ops (CPU default;
  also the cross-check oracle in tests/test_conv.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributedmnist_tpu.ops.conv import PatchConv, avg_pool2


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    conv_impl: str = "lax"          # {'lax', 'im2col'} — see module doc

    @nn.compact
    def __call__(self, x):
        if self.conv_impl == "im2col":
            def conv(feat, padding, name):
                return PatchConv(feat, (5, 5), padding=padding,
                                 dtype=self.dtype, name=name)
            pool = avg_pool2
        elif self.conv_impl == "lax":
            def conv(feat, padding, name):
                return nn.Conv(feat, (5, 5), padding=padding,
                               dtype=self.dtype, name=name)

            def pool(x):
                return nn.avg_pool(x, (2, 2), strides=(2, 2))
        else:
            # A typo must fail loudly: silently taking the lax path would
            # hang forever on platforms whose conv backward can't compile
            # (the reason the im2col path exists — ops/conv.py).
            raise ValueError(
                f"unknown conv_impl {self.conv_impl!r} "
                "(expected 'im2col' or 'lax')")
        x = x.astype(self.dtype)                       # (B, 28, 28, 1)
        x = conv(6, "SAME", "conv1")(x)                # (B, 28, 28, 6)
        x = nn.relu(x)
        x = pool(x)                                    # (B, 14, 14, 6)
        x = conv(16, "VALID", "conv2")(x)              # (B, 10, 10, 16)
        x = nn.relu(x)
        x = pool(x)                                    # (B, 5, 5, 16)
        x = x.reshape((x.shape[0], -1))                # (B, 400)
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
