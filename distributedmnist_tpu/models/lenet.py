"""LeNet-5 CNN — BASELINE.json configs 2/4/5's model.

Classic LeCun-98 LeNet-5 adapted to 28x28 MNIST input (the original takes
32x32, so conv1 uses SAME padding): conv 5x5x6 -> avgpool 2 -> conv 5x5x16
(VALID) -> avgpool 2 -> flatten(400) -> Dense(120) -> Dense(84) -> Dense(10).
61,706 parameters (pinned by test). relu instead of tanh — the standard
modern variant, and what gets MNIST past 99% (SURVEY.md §7.3 notes LeNet-5
is the model the wall-clock-to-99% harness must default to).

TPU notes: convs lower straight to the MXU via XLA conv ops — no custom
kernels needed (SURVEY.md §2 row 3). NHWC layout throughout (TPU-native).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)                       # (B, 28, 28, 1)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype,
                    name="conv1")(x)                   # (B, 28, 28, 6)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))     # (B, 14, 14, 6)
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype,
                    name="conv2")(x)                   # (B, 10, 10, 16)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))     # (B, 5, 5, 16)
        x = x.reshape((x.shape[0], -1))                # (B, 400)
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
