"""2-layer MLP, 784-128-10 — BASELINE.json config 1's model.

Exact architecture from the spec string "2-layer MLP (784-128-10)":
flatten -> Dense(128) -> relu -> Dense(10). Parameter count is pinned by a
unit test to 784*128+128 + 128*10+10 = 101,770 (SURVEY.md §2 row 2).

The hidden layer can route through the fused Pallas dense+relu kernel
(ops/fused.py) — one MXU pass with the bias-add and relu fused in the
kernel epilogue instead of separate HBM round-trips. XLA usually fuses
these anyway; the Pallas path exists to pin the fusion and as the
framework's exemplar custom kernel. `fused="auto"` uses Pallas on TPU only.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributedmnist_tpu.ops import fused


class MLP(nn.Module):
    hidden: int = 128
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    fused: str = fused.XLA  # a RESOLVED mode (ops.fused.resolve output)

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)  # (B, 784)
        if self.fused in (fused.PALLAS, fused.PALLAS_INTERPRET):
            w = self.param("hidden_kernel", nn.initializers.lecun_normal(),
                           (x.shape[-1], self.hidden), self.dtype)
            b = self.param("hidden_bias", nn.initializers.zeros,
                           (self.hidden,), self.dtype)
            x = fused.dense_relu(x, w, b,
                                 self.fused == fused.PALLAS_INTERPRET)
        else:
            x = nn.Dense(self.hidden, dtype=self.dtype, name="hidden")(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
