"""int32 pixel packing for the device-resident dataset.

The hot-loop batch gather selects rows of the device-resident train set by
index. On this TPU a row gather is element-count-bound, not byte-bound:
gathering 196 int32 words per image is ~free while gathering the same 784
bytes as uint8 costs ~0.11 ms per step at batch 512 (measured round 2,
scripts/profile_step.py — the uint8 layout tiles poorly). Packing 4 pixels
per int32 word therefore removes the gather from the step's critical path
entirely; the unpack (shift/mask, one elementwise op) fuses into the
normalization and first conv/matmul.

Byte order is little-endian within each word on both sides (numpy view on
the host, shift/mask in XLA), so packed and unpacked paths produce
bit-identical pixels — pinned by tests/test_packing.py, which also pins
trajectory equality of training runs in both formats.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

PIXELS = 28 * 28          # 784 uint8 pixels per image
WORDS = PIXELS // 4       # 196 int32 words per image


def pack_rows(x: np.ndarray) -> np.ndarray:
    """(N, 28, 28, 1) uint8 -> (N, 196) int32, 4 pixels per word
    (little-endian byte order within each word)."""
    if x.dtype != np.uint8:
        raise ValueError(f"expected uint8 pixels, got {x.dtype}")
    n = x.shape[0]
    flat = np.ascontiguousarray(x).reshape(n, PIXELS)
    return flat.view("<u4").astype(np.int32).reshape(n, WORDS)


def unpack_rows(words: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """(..., 196) int32 -> (..., 28, 28, 1) `dtype` in [0, 1] (the /255
    normalization is fused here so XLA folds unpack+normalize into the
    consumer). Inverse of pack_rows, bit-exact per pixel."""
    w = words.astype(jnp.uint32)
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    b = (w[..., None] >> shifts) & jnp.uint32(0xFF)     # (..., 196, 4)
    x = b.reshape(*words.shape[:-1], 28, 28, 1).astype(dtype)
    return x / jnp.asarray(255.0, dtype)
