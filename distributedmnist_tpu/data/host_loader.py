"""Streaming host->device input pipeline — the per-host loader for datasets
too large to live device-resident.

The default pipeline (loader.DeviceDataset + IndexStream) keeps the whole
dataset in HBM and moves only indices — optimal at MNIST scale. This module
is the general form the reference's shard-by-rank DataLoader takes when the
dataset outgrows HBM [BASELINE.json north_star: "per-host tf.data pipeline
feeding device-sharded global batches"]: jax.make_array_from_callback is
handed a per-device row-gather callback, so each process only ever
materializes the rows of its own devices' 'data' slices — no process builds
the full global batch and there is no cross-host data movement.

Batch order is IDENTICAL to the device-resident pipeline (same seeded
epoch permutations via IndexStream's index math), so the two pipelines are
interchangeable mid-training and equivalence-tested against each other.
jax async dispatch overlaps the host gather/transfer of block k+1 with the
device compute of block k.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedmnist_tpu.data.loader import IndexStream


class HostStream:
    """Yields (x_block, y_block) device arrays of shape (K, B, ...) with
    the batch axis sharded over 'data'.

    Two host-gather backends (identical batch order, equivalence-tested):

    - 'numpy' (default): the device placement callback gathers rows
      directly from the numpy arrays, per device shard.
    - 'tfdata': blocks flow through a tf.data pipeline (tf.gather mapped
      over the index blocks, prefetch(2)) — the literal "per-host tf.data
      pipeline feeding device-sharded global batches" named in
      BASELINE.json's north_star. The pipeline's background threads
      overlap block k+1's host gather with block k's device compute.
      tf.data materializes the whole (k, B, ...) block on the host, so
      the numpy backend remains the one that scales to multi-host
      datasets where no process may hold a full global batch.
    """

    def __init__(self, train_x: np.ndarray, train_y: np.ndarray,
                 global_batch: int, seed: int, mesh: Mesh,
                 start_step: int = 0, source: str = "numpy"):
        if source not in ("numpy", "tfdata"):
            raise ValueError(f"unknown host-stream source {source!r} "
                             "(expected 'numpy' or 'tfdata')")
        self.train_x = train_x
        self.train_y = train_y
        self.mesh = mesh
        self.source = source
        # Reuse IndexStream's seeded epoch-permutation math so batch order
        # matches the device-resident pipeline exactly.
        self.indices = IndexStream(train_x.shape[0], global_batch, seed,
                                   mesh, start_step=start_step)
        self._tf_iter = None        # lazy (tfdata): (block_k, iterator)

    @property
    def step(self) -> int:
        return self.indices.step

    def _put(self, idx: np.ndarray, x_host, y_host):
        import jax
        sharding = NamedSharding(self.mesh, P(None, "data"))

        def put(arr, gathered):
            shape = idx.shape + arr.shape[1:]
            if gathered is not None:
                # tfdata: block already gathered; callback just slices.
                return jax.make_array_from_callback(
                    shape, sharding, lambda s: gathered[s[0], s[1]])
            # numpy: each device (and therefore each process) gathers
            # ONLY the rows of its own 'data' slice — no process ever
            # materializes the full global batch on the host, which is
            # the point of the streaming pipeline at multi-host scale.
            return jax.make_array_from_callback(
                shape, sharding, lambda s: arr[idx[s[0], s[1]]])

        return put(self.train_x, x_host), put(self.train_y, y_host)

    def _tf_blocks(self, k: int):
        """tf.data pipeline yielding gathered (x, y) blocks of k steps,
        reading index blocks from a private IndexStream clone so the
        pipeline can prefetch ahead of the training loop."""
        import tensorflow as tf
        tf.config.set_visible_devices([], "GPU")   # host-only pipeline
        lookahead = IndexStream(
            self.indices.train_n, self.indices.global_batch,
            self.indices.seed, self.mesh, start_step=self.indices.step)

        def gen():
            while True:
                yield lookahead.host_block(k)

        ds = tf.data.Dataset.from_generator(
            gen, output_signature=tf.TensorSpec(
                (k, self.indices.global_batch), tf.int32))
        ds = ds.map(
            lambda i: (tf.gather(self.train_x, i),
                       tf.gather(self.train_y, i)),
            num_parallel_calls=tf.data.AUTOTUNE)
        return iter(ds.prefetch(2))

    def next_block(self, k: int):
        if self.source == "numpy":
            return self._put(self.indices.host_block(k), None, None)
        if self._tf_iter is None or self._tf_iter[0] != k:
            # Block size changed (e.g. the final remainder block): dispose
            # the old pipeline BEFORE building its replacement — dropping
            # the only reference reclaims its background threads and
            # prefetched blocks now, not whenever GC next runs with two
            # live pipelines. Order parity is unaffected: the canonical
            # IndexStream below is the sole batch-order authority.
            self._tf_iter = None
            self._tf_iter = (k, self._tf_blocks(k))
        x_t, y_t = next(self._tf_iter[1])
        # Advance the canonical stream (order authority) in lock-step.
        idx = self.indices.host_block(k)
        return self._put(idx, x_t.numpy(), y_t.numpy())
