"""Streaming host->device input pipeline — the per-host loader for datasets
too large to live device-resident.

The default pipeline (loader.DeviceDataset + IndexStream) keeps the whole
dataset in HBM and moves only indices — optimal at MNIST scale. This module
is the general form the reference's shard-by-rank DataLoader takes when the
dataset outgrows HBM [BASELINE.json north_star: "per-host tf.data pipeline
feeding device-sharded global batches"]: jax.make_array_from_callback is
handed a per-device row-gather callback, so each process only ever
materializes the rows of its own devices' 'data' slices — no process builds
the full global batch and there is no cross-host data movement.

Batch order is IDENTICAL to the device-resident pipeline (same seeded
epoch permutations via IndexStream's index math), so the two pipelines are
interchangeable mid-training and equivalence-tested against each other.
jax async dispatch overlaps the host gather/transfer of block k+1 with the
device compute of block k.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedmnist_tpu.data.loader import IndexStream


class HostStream:
    """Yields (x_block, y_block) device arrays of shape (K, B, ...) with
    the batch axis sharded over 'data'."""

    def __init__(self, train_x: np.ndarray, train_y: np.ndarray,
                 global_batch: int, seed: int, mesh: Mesh,
                 start_step: int = 0):
        self.train_x = train_x
        self.train_y = train_y
        self.mesh = mesh
        # Reuse IndexStream's seeded epoch-permutation math so batch order
        # matches the device-resident pipeline exactly.
        self.indices = IndexStream(train_x.shape[0], global_batch, seed,
                                   mesh, start_step=start_step)

    @property
    def step(self) -> int:
        return self.indices.step

    def next_block(self, k: int):
        import jax
        idx = self.indices.host_block(k)

        def put(arr):
            # Per-device callback: each device (and therefore each process)
            # gathers ONLY the rows of its own 'data' slice — no process
            # ever materializes the full global batch on the host, which is
            # the point of the streaming pipeline at multi-host scale.
            shape = idx.shape + arr.shape[1:]
            sharding = NamedSharding(self.mesh, P(None, "data"))
            return jax.make_array_from_callback(
                shape, sharding, lambda s: arr[idx[s[0], s[1]]])

        return put(self.train_x), put(self.train_y)
