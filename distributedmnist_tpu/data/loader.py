"""Device-resident dataset + sharded per-step index streams.

TPU-native inversion of the reference's "shard-by-rank DataLoader"
[BASELINE.json north_star]: instead of each rank's host process reading and
batching its slice of MNIST, the entire (tiny) dataset is pushed to device
HBM once as uint8 (~47 MB for train), and each step a *global-batch index
array* — sharded over the 'data' mesh axis — selects rows with an on-device
gather inside the jitted step. Normalization (cast + /255) happens in-step so
XLA fuses it with the first matmul/conv and the host never touches pixels in
the hot loop. A TPU MNIST step is ~100µs; any per-step host work would
dominate (SURVEY.md §7.3), which is why batches are *indices*, not arrays.

Determinism: batch order is a function of (seed, epoch) only — independent of
device count — which is what makes the seed-for-seed 1-chip ≡ N-chip
equivalence test possible (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class DeviceDataset:
    """Train/test arrays placed on devices, replicated over the mesh.

    Replication (not sharding) of the dataset is deliberate: a per-step
    gather of arbitrary global indices from a row-sharded array would need an
    all-to-all; from a replicated array it is a local gather, and only the
    tiny index array is sharded. For MNIST-scale data (<50 MB uint8) HBM
    replication is free; the batch that results from the gather IS sharded
    over 'data' because the indices are.
    """

    def __init__(self, data: dict, mesh: Mesh,
                 device_resident_train: bool = True,
                 pixel_format: str = "u8"):
        from distributedmnist_tpu.parallel import distributed
        if pixel_format not in ("u8", "packed"):
            raise ValueError(f"unknown pixel format {pixel_format!r} "
                             "(expected 'u8' or 'packed')")
        self.mesh = mesh
        self.source = data.get("source", "unknown")
        self.pixel_format = pixel_format
        # The streaming pipeline (host_loader.py) keeps train data on the
        # host; only the (small) test set goes to HBM then.
        if device_resident_train:
            train_x = data["train_x"]
            if pixel_format == "packed":
                # 4 pixels per int32 word: the per-step row gather of the
                # packed layout is ~free where the uint8 layout costs
                # ~0.11 ms/step (data/packing.py).
                from distributedmnist_tpu.data.packing import pack_rows
                train_x = pack_rows(train_x)
            self.train_x = distributed.put_replicated(train_x, mesh)
            self.train_y = distributed.put_replicated(data["train_y"], mesh)
        else:
            self.train_x = None
            self.train_y = None
        # Eval runs at low cadence; the test set stays uint8 images.
        self.test_x = distributed.put_replicated(data["test_x"], mesh)
        self.test_y = distributed.put_replicated(data["test_y"], mesh)
        self.train_n = int(data["train_x"].shape[0])
        self.test_n = int(data["test_x"].shape[0])


class IndexStream:
    """Seeded stream of global-batch index arrays, sharded over 'data'.

    Epoch semantics match a classic shuffling DataLoader with
    drop_last=True: each epoch is a fresh seeded permutation of the train
    set, cut into global batches. The permutation depends only on
    (seed, epoch), never on device or process count.

    Multi-host: every process computes the same permutation (same seed);
    parallel/distributed.put_global hands each device exactly its 'data'
    slice of the index array — the config-5 (multi-host) seam.
    """

    def __init__(self, train_n: int, global_batch: int, seed: int,
                 mesh: Mesh, start_step: int = 0):
        if global_batch > train_n:
            raise ValueError(f"global batch {global_batch} > dataset {train_n}")
        self.train_n = train_n
        self.global_batch = global_batch
        self.seed = seed
        self.mesh = mesh
        self.steps_per_epoch = train_n // global_batch
        self.step = start_step
        self._perm_cache: tuple[int, np.ndarray] | None = None

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        # Cached per epoch: a fresh 60k permutation every step would be
        # ~1 ms of host work against a ~100 µs TPU step.
        if self._perm_cache is None or self._perm_cache[0] != epoch:
            perm = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])
            ).permutation(self.train_n)
            self._perm_cache = (epoch, perm)
        return self._perm_cache[1]

    def indices_for_step(self, step: int) -> np.ndarray:
        epoch, k = divmod(step, self.steps_per_epoch)
        perm = self._epoch_perm(epoch)
        return perm[k * self.global_batch:(k + 1) * self.global_batch]

    def __iter__(self) -> Iterator[jax.Array]:
        return self

    def host_block(self, k: int) -> np.ndarray:
        """Host-side (k, global_batch) int32 index block for the next k
        steps; advances the stream. The single source of batch order for
        BOTH pipelines — device-resident (next_block) and streaming
        (host_loader.HostStream) — so their order parity is structural,
        not duplicated."""
        idx = np.stack([self.indices_for_step(self.step + i)
                        for i in range(k)]).astype(np.int32)
        self.step += k
        return idx

    def next_block(self, k: int) -> jax.Array:
        """Indices for the next k steps as one (k, global_batch) array,
        sharded P(None, 'data') — the K axis is scanned on device (one
        dispatch per block), the batch axis is split across chips."""
        from distributedmnist_tpu.parallel import distributed
        return distributed.put_global(
            self.host_block(k),
            NamedSharding(self.mesh, P(None, "data")))

    def __next__(self) -> jax.Array:
        return self.next_block(1)


def eval_batches(test_n: int, batch: int) -> tuple[np.ndarray, np.ndarray]:
    """Index matrix (n_batches, batch) covering the test set plus a bool
    mask of the same shape; tail padding (index 0 repeated) is masked False
    so it never enters the accuracy numerator."""
    n_batches = (test_n + batch - 1) // batch
    pos = np.arange(n_batches * batch).reshape(n_batches, batch)
    mask = pos < test_n
    idx = np.where(mask, pos, 0).astype(np.int32)
    return idx, mask
