"""MNIST loading: IDX / npz readers plus a deterministic synthetic fallback.

Parity target: the reference's "shard-by-rank DataLoader" over MNIST
[BASELINE.json north_star; reference mount empty — SURVEY.md §0]. The sharding
itself is NOT done here: on TPU the whole (tiny) dataset lives device-resident
and per-step *index* arrays are sharded over the mesh (see loader.py), which
is the idiomatic inversion of a per-rank DataLoader.

This environment has no network and no MNIST files on disk (SURVEY.md §7.1),
so `load_mnist` falls back to `synthetic_mnist`: a seeded, learnable,
digit-like 10-class problem with the exact MNIST shapes/dtypes. Runs that use
the synthetic path report it in their metrics (`data=synthetic`).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

TRAIN_N = 60_000
TEST_N = 10_000
IMG_SHAPE = (28, 28, 1)
NUM_CLASSES = 10

# Canonical IDX filenames (either raw or .gz).
_IDX_FILES = {
    "train_x": "train-images-idx3-ubyte",
    "train_y": "train-labels-idx1-ubyte",
    "test_x": "t10k-images-idx3-ubyte",
    "test_y": "t10k-labels-idx1-ubyte",
}


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (the MNIST distribution format), raw or gzipped.

    Raw files go through the native C++ reader when the toolchain built it
    (data/native/); gzipped files and toolchain-less environments use this
    Python parser. Both produce identical arrays (tested)."""
    if not path.endswith(".gz"):
        from distributedmnist_tpu.data import native
        arr = native.read_idx(path) if native.available() else None
        if arr is not None:
            return arr
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        if dtype_code != 0x08:  # unsigned byte — only type MNIST uses
            raise ValueError(f"{path}: unsupported IDX dtype 0x{dtype_code:02x}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _find(data_dir: str, base: str) -> Optional[str]:
    for name in (base, base + ".gz"):
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def _load_idx_dir(data_dir: str) -> Optional[dict]:
    paths = {k: _find(data_dir, v) for k, v in _IDX_FILES.items()}
    if not all(paths.values()):
        return None
    out = {k: _read_idx(p) for k, p in paths.items()}
    out["train_x"] = out["train_x"].reshape(-1, *IMG_SHAPE)
    out["test_x"] = out["test_x"].reshape(-1, *IMG_SHAPE)
    return out


def _load_npz(data_dir: str) -> Optional[dict]:
    """keras-style mnist.npz: arrays x_train, y_train, x_test, y_test."""
    p = os.path.join(data_dir, "mnist.npz")
    if not os.path.exists(p):
        return None
    with np.load(p) as z:
        return {
            "train_x": z["x_train"].astype(np.uint8).reshape(-1, *IMG_SHAPE),
            "train_y": z["y_train"].astype(np.int32),
            "test_x": z["x_test"].astype(np.uint8).reshape(-1, *IMG_SHAPE),
            "test_y": z["y_test"].astype(np.int32),
        }


def synthetic_mnist(seed: int = 0, train_n: int = TRAIN_N,
                    test_n: int = TEST_N, noise: float = 0.44,
                    jitter: int = 3) -> dict:
    """Deterministic, learnable, digit-like 10-class dataset.

    Each class is a smooth random template (low-frequency blobs, like pen
    strokes); a sample is its class template under a small random affine-ish
    jitter (translation up to ±`jitter` px) plus Gaussian pixel noise of
    scale `noise`.

    The default (noise=0.44, jitter=3) is CALIBRATED so the task's
    difficulty matches real MNIST's headline numbers (BASELINE.md
    "Synthetic vs real MNIST" section; scripts/calibrate_synthetic.py
    reproduces the sweep): at 60k/10k scale (6 epochs, Adam+cosine) an
    MLP 784-128-10 reaches 98.3% test accuracy while LeNet-5 reaches
    99.1% — mirroring the canonical published MNIST results for the same
    models (~97.5-98.4% MLP vs ~99.0-99.3% LeNet-5, LeCun et al. 1998 and
    common reproductions). This makes "wall-clock to 99% on synthetic" an
    honest stand-in for the real-MNIST target when no real data is
    mountable (SURVEY.md §7.3): the 99% bar is reachable by the conv
    model but NOT by the dense-only one, exactly as on MNIST.
    """
    rng = np.random.default_rng(seed)
    # Low-frequency class templates: upsampled 7x7 noise -> 28x28.
    low = rng.normal(size=(NUM_CLASSES, 7, 7))
    templates = np.kron(low, np.ones((4, 4)))           # (10, 28, 28)
    # Smooth with a box blur to look stroke-like.
    k = np.ones((3, 3)) / 9.0
    for c in range(NUM_CLASSES):
        t = templates[c]
        padded = np.pad(t, 1, mode="edge")
        sm = sum(padded[i:i + 28, j:j + 28] * k[i, j]
                 for i in range(3) for j in range(3))
        templates[c] = sm
    templates = (templates - templates.min(axis=(1, 2), keepdims=True))
    templates /= templates.max(axis=(1, 2), keepdims=True) + 1e-9

    def make(n, rng):
        y = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        base = templates[y]                              # (n, 28, 28)
        # per-sample translation jitter in [-jitter, jitter] px
        sx = rng.integers(-jitter, jitter + 1, size=n)
        sy = rng.integers(-jitter, jitter + 1, size=n)
        x = np.empty_like(base)
        for dx in range(-jitter, jitter + 1):
            for dy in range(-jitter, jitter + 1):
                m = (sx == dx) & (sy == dy)
                if m.any():
                    x[m] = np.roll(np.roll(base[m], dx, axis=1), dy, axis=2)
        x = x + rng.normal(scale=noise, size=x.shape)
        x = np.clip(x, 0.0, 1.0)
        return (x * 255).astype(np.uint8).reshape(n, *IMG_SHAPE), y

    train_x, train_y = make(train_n, np.random.default_rng(seed + 1))
    test_x, test_y = make(test_n, np.random.default_rng(seed + 2))
    return {"train_x": train_x, "train_y": train_y,
            "test_x": test_x, "test_y": test_y, "source": "synthetic"}


def load_mnist(data_dir: Optional[str] = None, synthetic: bool = False,
               seed: int = 0) -> dict:
    """Load MNIST as uint8 images (N,28,28,1) + int32 labels.

    Order of preference: IDX files in data_dir, mnist.npz in data_dir,
    synthetic fallback. Returned dict carries a "source" key so runs can
    report which path they used (real 99% targets require real MNIST —
    SURVEY.md §7.3).
    """
    if not synthetic and data_dir:
        for fn in (_load_idx_dir, _load_npz):
            out = fn(data_dir)
            if out is not None:
                out["train_y"] = out["train_y"].astype(np.int32)
                out["test_y"] = out["test_y"].astype(np.int32)
                out["source"] = "real"
                return out
        raise FileNotFoundError(
            f"no MNIST IDX files or mnist.npz under {data_dir!r}")
    return synthetic_mnist(seed=seed)
