from distributedmnist_tpu.data.mnist import load_mnist, synthetic_mnist  # noqa: F401
from distributedmnist_tpu.data.loader import (  # noqa: F401
    DeviceDataset,
    IndexStream,
)
