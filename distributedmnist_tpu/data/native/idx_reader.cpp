// Native IDX (MNIST distribution format) reader, exposed to Python via
// ctypes (distributedmnist_tpu/data/native/__init__.py).
//
// Role: the reference's data path is backed by native code (torch's C++
// DataLoader machinery); this is the framework's native equivalent for the
// host-side IO it actually has. The hot path on TPU is the on-device index
// gather (data/loader.py) — host IO happens once at startup, so this
// component optimizes cold-start: a single mmap-free streamed read with no
// intermediate Python objects.
//
// ABI (stable, C):
//   idx_probe(path, out_ndim, out_dims[4])         -> 0 ok | <0 errno-ish
//   idx_read(path, out_buf, buf_len)               -> bytes read | <0 error

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr int kErrOpen = -1;
constexpr int kErrMagic = -2;
constexpr int kErrTrunc = -3;
constexpr int kErrSmallBuf = -4;

// Big-endian u32 read.
uint32_t be32(const unsigned char *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

struct Header {
  int ndim;
  uint64_t dims[4];
  uint64_t total;
};

int read_header(FILE *f, Header *h) {
  unsigned char buf[4];
  if (fread(buf, 1, 4, f) != 4) return kErrTrunc;
  uint32_t magic = be32(buf);
  // IDX magic: 0x00 0x00 <dtype> <ndim>; MNIST uses dtype 0x08 (u8).
  if ((magic & 0xFFFF0000u) != 0 || ((magic >> 8) & 0xFF) != 0x08)
    return kErrMagic;
  h->ndim = int(magic & 0xFF);
  if (h->ndim < 1 || h->ndim > 4) return kErrMagic;
  h->total = 1;
  for (int i = 0; i < h->ndim; ++i) {
    if (fread(buf, 1, 4, f) != 4) return kErrTrunc;
    h->dims[i] = be32(buf);
    h->total *= h->dims[i];
  }
  return 0;
}

}  // namespace

extern "C" {

int idx_probe(const char *path, int *out_ndim, uint64_t *out_dims) {
  FILE *f = fopen(path, "rb");
  if (!f) return kErrOpen;
  Header h;
  int rc = read_header(f, &h);
  fclose(f);
  if (rc) return rc;
  *out_ndim = h.ndim;
  for (int i = 0; i < h.ndim; ++i) out_dims[i] = h.dims[i];
  return 0;
}

long long idx_read(const char *path, unsigned char *out, long long cap) {
  FILE *f = fopen(path, "rb");
  if (!f) return kErrOpen;
  Header h;
  int rc = read_header(f, &h);
  if (rc) {
    fclose(f);
    return rc;
  }
  if (uint64_t(cap) < h.total) {
    fclose(f);
    return kErrSmallBuf;
  }
  size_t got = fread(out, 1, h.total, f);
  fclose(f);
  if (got != h.total) return kErrTrunc;
  return (long long)got;
}

}  // extern "C"
