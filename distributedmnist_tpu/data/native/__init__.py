"""ctypes binding for the native IDX reader (idx_reader.cpp).

The shared library is built explicitly via `ensure_built()` (g++, atomic
rename, cross-process safe); the data path only USES the library when it is
already present (`available()` never triggers a compile), so a fresh
checkout's cold start is never blocked behind a g++ subprocess. Every entry
point returns None when the library is unavailable and callers fall back to
the pure-Python parser. See idx_reader.cpp's header comment for why this
component exists.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("distributedmnist_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "idx_reader.cpp")
_LIB = os.path.join(_DIR, "libidx_reader.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def ensure_built(force: bool = False) -> bool:
    """Compile the library if missing/stale. Atomic (temp file + rename) so
    concurrent builders in different processes can race harmlessly — each
    renames a complete .so into place. Returns availability."""
    if not os.path.exists(_SRC):
        return available()  # shipped .so without source: use as-is
    stale = (not os.path.exists(_LIB)
             or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
    if stale or force:
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
        except (OSError, subprocess.SubprocessError) as e:
            log.info("native idx_reader build failed (%s); Python path "
                     "remains active", e)
            return False
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return available()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB):
            return None
        try:
            lib = ctypes.CDLL(_LIB)
            lib.idx_probe.restype = ctypes.c_int
            lib.idx_probe.argtypes = [ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_int),
                                      ctypes.POINTER(ctypes.c_uint64)]
            lib.idx_read.restype = ctypes.c_longlong
            lib.idx_read.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_ubyte),
                                     ctypes.c_longlong]
        except (OSError, AttributeError) as e:
            # Corrupt/incompatible .so (e.g. interrupted build from an old
            # version): disable the native path rather than crash loading.
            log.warning("native idx_reader load failed (%s); using Python "
                        "path", e)
            _load_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    """True iff the already-built library is loadable. Never compiles."""
    return _load() is not None


def read_idx(path: str) -> Optional[np.ndarray]:
    """Read a raw (non-gzip) IDX file natively; None if the native path is
    unavailable (caller falls back to the Python parser)."""
    lib = _load()
    if lib is None:
        return None
    ndim = ctypes.c_int()
    dims = (ctypes.c_uint64 * 4)()
    rc = lib.idx_probe(path.encode(), ctypes.byref(ndim), dims)
    if rc != 0:
        raise ValueError(f"native idx_probe({path!r}) failed: rc={rc}")
    shape = tuple(int(dims[i]) for i in range(ndim.value))
    out = np.empty(shape, dtype=np.uint8)
    n = lib.idx_read(path.encode(),
                     out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                     out.size)
    if n != out.size:
        raise ValueError(f"native idx_read({path!r}) failed: rc={n}")
    return out
