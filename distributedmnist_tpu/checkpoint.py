"""Async checkpoint/restore via orbax — BASELINE.json config 5
("multi-host v4-32 data-parallel LeNet-5 with async checkpoint/restore");
SURVEY.md §2 row 10, §5.

Saves the full training state pytree {step, params, opt_state}
asynchronously: the device->host copy happens immediately, the disk write
overlaps subsequent training steps. orbax coordinates across processes in
multi-host runs (every process calls save/restore; process 0 owns the
directory commit), which replaces any hand-rolled rank-0-writes logic.

Restore-from-latest on startup is the framework's failure-recovery story
(paired with the --fail-at-step injection hook in the trainer, and the
kill/resume e2e test).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Tuple

import jax
import orbax.checkpoint as ocp

log = logging.getLogger("distributedmnist_tpu")


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self.mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        if step in self.mgr.all_steps():
            return False  # orbax raises on duplicate steps; saving is moot
        return self.mgr.save(step, args=ocp.args.StandardSave(state),
                             force=force)

    def maybe_restore(self, state: Any) -> Tuple[Any, bool]:
        """Restore the latest checkpoint into `state`'s structure (shapes,
        dtypes AND shardings preserved), or return `state` unchanged.

        A checkpoint written with the OTHER optimizer-state layout (flat
        single-vector vs per-leaf — config.flat_optimizer) is converted
        automatically: the moment vectors are raveled/unraveled between
        layouts (optax.flatten concatenates leaves in jax.tree.flatten
        order, so the conversion is exact), and training resumes
        bit-identically without an operator flag."""
        step = self.mgr.latest_step()
        if step is None:
            return state, False
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), state)
        try:
            restored = self.mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except (ValueError, TypeError, KeyError) as e:
            # Structure mismatches surface as ValueError/TypeError/
            # KeyError from orbax's tree handling (IO failures — a
            # half-written directory, permissions — raise OSError and
            # pass through untouched). The most common cause: the
            # checkpoint was written with the other optimizer-state
            # layout; try the exact flat<->per-leaf conversion before
            # giving up, and surface the knob instead of an opaque
            # pytree error if that fails too.
            restored = self._restore_other_layout(step, abstract)
            if restored is None:
                raise ValueError(
                    f"checkpoint at step {step} in {self.directory!r} "
                    "does not match this run's training-state structure "
                    "(and is not a flat<->per-leaf optimizer-layout "
                    f"variant of it); original error: {e}") from e
            log.info("restored checkpoint at step %d via flat<->per-leaf "
                     "optimizer-layout conversion", step)
        return restored, True

    def _restore_other_layout(self, step: int, abstract: Any):
        """Restore a checkpoint whose optimizer state was written in the
        other layout (optax.flatten's single vector per moment vs one
        array per param leaf) and convert it into `abstract`'s layout.
        Returns None if the checkpoint is not the other layout either."""
        import jax.numpy as jnp
        import numpy as np

        params_abs = abstract.params
        params_def = jax.tree.structure(params_abs)
        p_leaves = jax.tree.leaves(params_abs)
        flat_size = sum(p.size for p in p_leaves)

        def momentlike(x) -> bool:
            # a subtree shaped exactly like params (per-leaf moments)
            return (not isinstance(x, jax.ShapeDtypeStruct)
                    and not isinstance(x, jax.Array)
                    and jax.tree.structure(x) == params_def)

        def flatlike(x) -> bool:
            # a single raveled moment vector (optax.flatten's state)
            return getattr(x, "ndim", None) == 1 and x.size == flat_size

        target_flat = any(flatlike(l)
                          for l in jax.tree.leaves(abstract.opt_state))
        if target_flat:
            # source layout: per-leaf — expand each flat vector into a
            # params-shaped subtree (placed like the params themselves)
            def source_leaf(leaf):
                if flatlike(leaf):
                    return jax.tree.map(
                        lambda p: jax.ShapeDtypeStruct(
                            p.shape, leaf.dtype, sharding=p.sharding),
                        params_abs)
                return leaf
            src_opt = jax.tree.map(source_leaf, abstract.opt_state)
        else:
            # source layout: flat — collapse each params-shaped moment
            # subtree into one (flat_size,) vector, replicated like the
            # (scalar, replicated) step counter
            rep = abstract.step.sharding

            def source_sub(x):
                if momentlike(x):
                    return jax.ShapeDtypeStruct(
                        (flat_size,), p_leaves[0].dtype, sharding=rep)
                return x
            src_opt = jax.tree.map(source_sub, abstract.opt_state,
                                   is_leaf=momentlike)

        src_abstract = abstract.replace(opt_state=src_opt)
        try:
            src = self.mgr.restore(
                step, args=ocp.args.StandardRestore(src_abstract))
        except (ValueError, TypeError, KeyError):
            return None

        if target_flat:
            def to_target(x):
                if momentlike(x):
                    return jnp.concatenate(
                        [jnp.reshape(v, (-1,))
                         for v in jax.tree.leaves(x)])
                return x
            tgt_opt = jax.tree.map(to_target, src.opt_state,
                                   is_leaf=momentlike)
        else:
            offsets = np.cumsum([p.size for p in p_leaves])[:-1]

            def to_target(x):
                if flatlike(x):
                    parts = jnp.split(x, offsets)
                    return jax.tree.unflatten(
                        params_def,
                        [jnp.reshape(v, p.shape)
                         for v, p in zip(parts, p_leaves)])
                return x
            tgt_opt = jax.tree.map(to_target, src.opt_state)
        # final placement: every converted leaf takes the target sharding
        tgt_opt = jax.tree.map(
            lambda v, a: jax.device_put(v, a.sharding),
            tgt_opt, abstract.opt_state)
        return src.replace(opt_state=tgt_opt)

    def wait(self) -> None:
        self.mgr.wait_until_finished()

    def close(self) -> None:
        self.mgr.close()
