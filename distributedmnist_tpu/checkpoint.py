"""Async checkpoint/restore via orbax — BASELINE.json config 5
("multi-host v4-32 data-parallel LeNet-5 with async checkpoint/restore");
SURVEY.md §2 row 10, §5.

Saves the full training state pytree {step, params, opt_state}
asynchronously: the device->host copy happens immediately, the disk write
overlaps subsequent training steps. orbax coordinates across processes in
multi-host runs (every process calls save/restore; process 0 owns the
directory commit), which replaces any hand-rolled rank-0-writes logic.

Restore-from-latest on startup is the framework's failure-recovery story
(paired with the --fail-at-step injection hook in the trainer, and the
kill/resume e2e test).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Tuple

import jax
import orbax.checkpoint as ocp

log = logging.getLogger("distributedmnist_tpu")


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self.mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        if step in self.mgr.all_steps():
            return False  # orbax raises on duplicate steps; saving is moot
        return self.mgr.save(step, args=ocp.args.StandardSave(state),
                             force=force)

    def maybe_restore(self, state: Any) -> Tuple[Any, bool]:
        """Restore the latest checkpoint into `state`'s structure (shapes,
        dtypes AND shardings preserved), or return `state` unchanged."""
        step = self.mgr.latest_step()
        if step is None:
            return state, False
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), state)
        try:
            restored = self.mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except (ValueError, TypeError, KeyError) as e:
            # Structure mismatches surface as ValueError/TypeError/
            # KeyError from orbax's tree handling (IO failures — a
            # half-written directory, permissions — raise OSError and
            # pass through untouched). The most common cause: the
            # checkpoint was written with the other optimizer-state
            # layout (flat single-vector vs per-leaf —
            # config.flat_optimizer changed its default in round 2).
            # Surface the knob instead of an opaque pytree error.
            raise ValueError(
                f"checkpoint at step {step} in {self.directory!r} does "
                "not match this run's training-state structure. If it "
                "was written by a run with the other optimizer layout, "
                "retry with --no-flat-optimizer (or its inverse); "
                f"original error: {e}") from e
        return restored, True

    def wait(self) -> None:
        self.mgr.wait_until_finished()

    def close(self) -> None:
        self.mgr.close()
