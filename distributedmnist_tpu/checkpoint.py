"""Async checkpoint/restore via orbax — BASELINE.json config 5
("multi-host v4-32 data-parallel LeNet-5 with async checkpoint/restore");
SURVEY.md §2 row 10, §5.

Saves the full training state pytree {step, params, opt_state}
asynchronously: the device->host copy happens immediately, the disk write
overlaps subsequent training steps. orbax coordinates across processes in
multi-host runs (every process calls save/restore; process 0 owns the
directory commit), which replaces any hand-rolled rank-0-writes logic.

Restore-from-latest on startup is the framework's failure-recovery story
(paired with the --fail-at-step injection hook in the trainer, and the
kill/resume e2e test).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

log = logging.getLogger("distributedmnist_tpu")


def committed_steps(directory: str) -> list[int]:
    """Step numbers of checkpoints fully COMMITTED in `directory`. An
    in-progress async save lives in a tmp-suffixed dir, never an
    all-digit one, so the digit-only listing is exactly the committed
    set (the same invariant tests/conftest.py polls on)."""
    if not os.path.isdir(directory):
        return []
    return sorted(int(d) for d in os.listdir(directory) if d.isdigit())


def restore_latest_params(directory: str, abstract_params: Any,
                          step: Optional[int] = None
                          ) -> Tuple[Any, Optional[int]]:
    """Params-only restore for SERVING: read just the `params` subtree of
    the latest committed checkpoint, never touching the optimizer slots.

    A served model needs its weights, not its Adam moments — a full-state
    restore reads 3x the bytes (params + mu + nu) and holds the extra
    arrays until GC, which multiplies across every version a model
    registry keeps warm. This path hands orbax an `item` tree containing
    ONLY `params` (with `transforms={}` so unnamed checkpoint entries are
    skipped, not structure-checked): the opt_state/step bytes are never
    read from disk. It also makes serving restores layout-agnostic: a
    checkpoint written under either optimizer-state layout
    (config.flat_optimizer) serves identically, with none of
    maybe_restore()'s flat<->per-leaf conversion machinery involved.

    `abstract_params` is a params-shaped pytree of jax.ShapeDtypeStruct
    (shapes, dtypes AND target shardings). Returns (params, step), or
    (None, None) when the directory holds no committed checkpoint. A
    checkpoint whose params don't match the abstract tree raises
    ValueError naming the directory. Pass `step` to pin a specific
    committed step instead of the latest (callers that listed the
    directory themselves — e.g. an idempotency check — must restore the
    step they decided on, not whatever landed since).
    """
    if step is None:
        steps = committed_steps(directory)
        if not steps:
            return None, None
        step = steps[-1]
    # CheckpointManager writes each item under <dir>/<step>/<item_name>;
    # StandardSave's default item name is "default". Fall back to the
    # bare step dir for trees saved without the item wrapper.
    path = os.path.join(os.path.abspath(directory), str(step), "default")
    if not os.path.isdir(path):
        path = os.path.join(os.path.abspath(directory), str(step))
    item = {"params": abstract_params}
    restore_args = jax.tree.map(
        lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding,
                                       global_shape=x.shape,
                                       dtype=x.dtype), item)
    ckptr = ocp.PyTreeCheckpointer()
    try:
        restored = ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=item, restore_args=restore_args, transforms={}))
    except (ValueError, TypeError, KeyError) as e:
        raise ValueError(
            f"checkpoint at step {step} in {directory!r} has no params "
            "subtree matching this model's structure (params-only "
            f"serving restore); original error: {e}") from e
    finally:
        ckptr.close()
    params = restored["params"]
    # The transforms fallback is silently lenient: a requested path
    # ABSENT from the checkpoint is passed through as the abstract
    # placeholder instead of raising — a wrong-model checkpoint would
    # otherwise hand serving a Frankenstein tree of real arrays and
    # ShapeDtypeStructs. Validate every leaf restored, loudly.
    missing = [
        jax.tree_util.keystr(path)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        if isinstance(leaf, jax.ShapeDtypeStruct)]
    if missing:
        raise ValueError(
            f"checkpoint at step {step} in {directory!r} does not hold "
            f"params for this model: {len(missing)} leaf/leaves missing "
            f"(e.g. {missing[:3]}) — params-only serving restore "
            "requires an exact params-tree match")
    return params, step


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self.mgr = ocp.CheckpointManager(self.directory, options=options)
        # One directory scan per Checkpointer lifetime: save() consults this
        # in-memory set instead of re-listing the checkpoint dir on every
        # call (all_steps() is a synchronous metadata round-trip — costly
        # inside the training loop on slow shared storage). GC by
        # max_to_keep only ever removes steps, so a stale entry merely
        # skips a duplicate save, which is the intended behavior anyway.
        self._saved_steps = set(self.mgr.all_steps())

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        if step in self._saved_steps:
            return False  # orbax raises on duplicate steps; saving is moot
        saved = self.mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)
        if saved:
            self._saved_steps.add(step)
        return saved

    def maybe_restore(self, state: Any) -> Tuple[Any, bool]:
        """Restore the latest checkpoint into `state`'s structure (shapes,
        dtypes AND shardings preserved), or return `state` unchanged.

        A checkpoint written with the OTHER optimizer-state layout (flat
        single-vector vs per-leaf — config.flat_optimizer) is converted
        automatically: the moment vectors are raveled/unraveled between
        layouts (optax.flatten concatenates leaves in jax.tree.flatten
        order, so the conversion is exact), and training resumes
        bit-identically without an operator flag."""
        step = self.mgr.latest_step()
        if step is None:
            return state, False
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), state)
        try:
            restored = self.mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except (ValueError, TypeError, KeyError, AssertionError) as e:
            # Structure mismatches surface as ValueError/TypeError/
            # KeyError from orbax's tree handling — and, on some orbax
            # versions, as AssertionError ("Expected RestoreArgs or
            # SaveArgs") when the saved tree and the abstract target
            # disagree leaf-for-leaf (IO failures — a half-written
            # directory, permissions — raise OSError and pass through
            # untouched). The most common cause: the
            # checkpoint was written with the other optimizer-state
            # layout; try the exact flat<->per-leaf conversion before
            # giving up, and surface the knob instead of an opaque
            # pytree error if that fails too.
            restored = self._restore_other_layout(step, abstract)
            if restored is None:
                raise ValueError(
                    f"checkpoint at step {step} in {self.directory!r} "
                    "does not match this run's training-state structure "
                    "(and is not a flat<->per-leaf optimizer-layout "
                    f"variant of it); original error: {e}") from e
            log.info("restored checkpoint at step %d via flat<->per-leaf "
                     "optimizer-layout conversion", step)
        return restored, True

    def _restore_other_layout(self, step: int, abstract: Any):
        """Restore a checkpoint whose optimizer state was written in the
        other layout (optax.flatten's single vector per moment vs one
        array per param leaf) and convert it into `abstract`'s layout.
        Returns None if the checkpoint is not the other layout either.

        The other-layout hypothesis is gated on the checkpoint's OWN tree
        metadata (shapes on disk), not just size heuristics: the saved
        params must match the target's params exactly, and the saved
        opt_state's leaf shapes must match the hypothesized source layout
        leaf-for-leaf, before any second disk restore is attempted — so a
        future optimizer state with a coincidentally flat-sized 1-D leaf
        cannot be silently converted from garbage (round-3 verdict, weak
        #4). Each hypothesis leaf then takes its dtype from the
        corresponding saved leaf (positionally — mu and nu may have
        different dtypes, e.g. optax's mu_dtype), so the restore neither
        assumes the params' dtype nor casts moments behind the user's
        back; the final placement casts to the target's dtypes."""
        import jax.numpy as jnp
        import numpy as np

        params_abs = abstract.params
        params_def = jax.tree.structure(params_abs)
        p_leaves = jax.tree.leaves(params_abs)
        flat_size = sum(p.size for p in p_leaves)

        saved_opt = saved_params = None
        try:
            saved_tree = self.mgr.item_metadata(step).tree
            saved_opt = saved_tree["opt_state"]
            saved_params = saved_tree["params"]
        except Exception as e:  # metadata shape varies across orbax
            # versions; the restore below still validates structure —
            # but LOUDLY: without metadata the shape-fingerprint gate is
            # disabled and conversion falls back to orbax's own
            # structural validation only (round-4 advice).
            log.warning(
                "checkpoint metadata unavailable (%s: %s); the "
                "layout-conversion fingerprint gate is disabled for "
                "this restore", type(e).__name__, e)

        def _key_str(k) -> str:
            for attr in ("key", "name", "idx"):  # DictKey / GetAttrKey /
                if hasattr(k, attr):             # SequenceKey
                    return str(getattr(k, attr))
            return str(k)

        def _path_of(path) -> tuple:
            return tuple(_key_str(k) for k in path)

        def fingerprint(tree) -> list:
            # SORTED (normalized key path, shape) per leaf. Dict keys
            # (the saved metadata tree) and namedtuple fields (the live
            # optax state) normalize to the same strings, so equality
            # means leaf-for-leaf correspondence BY PATH. Sorting makes
            # the comparison flatten-order-independent: dicts flatten
            # sorted-by-key while namedtuples flatten in declaration
            # order, and adam/sgd fields being alphabetical today is a
            # coincidence the gate must not lean on (round-4 advice).
            # Shapes alone would be order-blind exactly where it
            # matters: mu and nu always share a shape.
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            return sorted((_path_of(path), tuple(leaf.shape))
                          for path, leaf in flat)

        if saved_params is not None and fingerprint(saved_params) != \
                fingerprint(params_abs):
            return None  # different model, not a layout variant

        def momentlike(x) -> bool:
            # a subtree shaped exactly like params (per-leaf moments)
            return (not isinstance(x, jax.ShapeDtypeStruct)
                    and not isinstance(x, jax.Array)
                    and jax.tree.structure(x) == params_def)

        def flatlike(x) -> bool:
            # a single raveled moment vector (optax.flatten's state)
            return getattr(x, "ndim", None) == 1 and x.size == flat_size

        target_flat = any(flatlike(l)
                          for l in jax.tree.leaves(abstract.opt_state))
        if target_flat:
            # source layout: per-leaf — expand each flat vector into a
            # params-shaped subtree (placed like the params themselves)
            def source_leaf(leaf):
                if flatlike(leaf):
                    return jax.tree.map(
                        lambda p: jax.ShapeDtypeStruct(
                            p.shape, leaf.dtype, sharding=p.sharding),
                        params_abs)
                return leaf
            src_opt = jax.tree.map(source_leaf, abstract.opt_state)
        else:
            # source layout: flat — collapse each params-shaped moment
            # subtree into one (flat_size,) vector, replicated like the
            # (scalar, replicated) step counter
            rep = abstract.step.sharding

            def source_sub(x):
                if momentlike(x):
                    return jax.ShapeDtypeStruct(
                        (flat_size,), p_leaves[0].dtype, sharding=rep)
                return x
            src_opt = jax.tree.map(source_sub, abstract.opt_state,
                                   is_leaf=momentlike)

        if saved_opt is not None:
            # Structural fingerprint gate: only hit the disk again when
            # the checkpoint's on-disk opt_state matches the hypothesized
            # source layout leaf for leaf — key paths AND shapes ...
            if fingerprint(saved_opt) != fingerprint(src_opt):
                return None
            # ... and then each hypothesis leaf reads with the dtype the
            # checkpoint actually holds at the SAME KEY PATH (not the
            # same flatten position — the two trees may flatten in
            # different orders; the fingerprint match above guarantees
            # the path sets coincide).
            saved_flat = jax.tree_util.tree_flatten_with_path(
                saved_opt)[0]
            saved_dtypes = {
                _path_of(path): np.dtype(leaf.dtype)
                for path, leaf in saved_flat}
            # Normalized key paths must be unique: _key_str's str(k)
            # fallback makes collisions possible for exotic key types,
            # and a collision would silently overwrite one leaf's dtype
            # with another's — the restore then picks a wrong dtype and
            # fails structurally without saying why (ADVICE r5). Fail
            # loudly at the source instead.
            assert len(saved_dtypes) == len(saved_flat), (
                "normalized opt_state key paths collide "
                f"({len(saved_flat)} leaves -> {len(saved_dtypes)} "
                "distinct paths); _key_str cannot disambiguate this "
                "checkpoint's tree")
            src_flat, src_def = jax.tree_util.tree_flatten_with_path(
                src_opt)
            src_opt = jax.tree.unflatten(src_def, [
                jax.ShapeDtypeStruct(h.shape, saved_dtypes[_path_of(path)],
                                     sharding=h.sharding)
                for path, h in src_flat])

        src_abstract = abstract.replace(opt_state=src_opt)
        try:
            src = self.mgr.restore(
                step, args=ocp.args.StandardRestore(src_abstract))
        except (ValueError, TypeError, KeyError, AssertionError):
            # Same exception surface as the first restore attempt; the
            # collision assert above raises BEFORE this try, so it
            # cannot be swallowed here.
            return None

        if target_flat:
            def to_target(x):
                if momentlike(x):
                    return jnp.concatenate(
                        [jnp.reshape(v, (-1,))
                         for v in jax.tree.leaves(x)])
                return x
            tgt_opt = jax.tree.map(to_target, src.opt_state,
                                   is_leaf=momentlike)
        else:
            offsets = np.cumsum([p.size for p in p_leaves])[:-1]

            def to_target(x):
                if flatlike(x):
                    parts = jnp.split(x, offsets)
                    return jax.tree.unflatten(
                        params_def,
                        [jnp.reshape(v, p.shape)
                         for v, p in zip(parts, p_leaves)])
                return x
            tgt_opt = jax.tree.map(to_target, src.opt_state)
        # final placement: every converted leaf takes the target sharding
        # and dtype (the cast covers a checkpoint whose moments were saved
        # in a different dtype than this run's optimizer expects)
        tgt_opt = jax.tree.map(
            lambda v, a: jax.device_put(
                v if v.dtype == a.dtype else v.astype(a.dtype),
                a.sharding),
            tgt_opt, abstract.opt_state)
        return src.replace(opt_state=tgt_opt)

    def wait(self) -> None:
        self.mgr.wait_until_finished()

    def close(self) -> None:
        self.mgr.close()
