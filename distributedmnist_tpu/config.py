"""Configuration: frozen dataclass + the five named presets.

The five presets are exactly the five workloads the reference must support
per BASELINE.json `configs` (reference mount is empty; BASELINE.json is the
authoritative capability spec — SURVEY.md §0):

1. single-process 2-layer MLP (784-128-10) on MNIST, SGD, batch=64
2. single-process LeNet-5 CNN on MNIST, Adam
3. 2-worker data-parallel MLP with gradient allreduce
4. 8-chip data-parallel LeNet-5, per-rank sharding, global batch=512
5. multi-host v4-32 data-parallel LeNet-5 with async checkpoint/restore
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Config:
    # model / optimizer
    model: str = "lenet"            # {mlp, lenet}
    optimizer: str = "adam"         # {sgd, adam}
    learning_rate: float = 1e-3
    momentum: float = 0.9           # used by sgd only
    lr_schedule: str = "constant"   # {constant, cosine, warmup-cosine}
    warmup_steps: int = 0
    # cosine decay horizon in steps. None = the run's own total step count
    # (epochs x steps_per_epoch, or --steps). Pinning it decouples the LR
    # schedule from the trial-budget knobs: a tuned recipe keeps the exact
    # decay curve its evidence was collected under even when --max-epochs/
    # --steps change (bench.py time-to-accuracy pins this).
    lr_decay_steps: Optional[int] = None
    # data
    data_dir: Optional[str] = None  # dir with IDX (*-ubyte[.gz]) or mnist.npz
    synthetic: bool = False         # force deterministic synthetic MNIST
    batch_size: int = 512           # GLOBAL batch size (split across chips)
    # "device": whole train set HBM-resident, on-device index gather (the
    # MNIST-optimal default). "stream": per-host streaming batches for
    # datasets that outgrow HBM (data/host_loader.py). Same batch order.
    data_pipeline: str = "device"
    # host-gather backend of the streaming pipeline: "numpy" (per-device
    # row gathers, multi-host-scalable) or "tfdata" (tf.data pipeline
    # with background prefetch — the north_star's literal per-host
    # tf.data loader). Identical batch order (equivalence-tested).
    stream_source: str = "numpy"
    # device-resident train-set layout: "packed" stores 4 uint8 pixels
    # per int32 word, making the per-step on-device row gather ~free
    # (vs ~0.11 ms/step for uint8 rows at batch 512 — data/packing.py);
    # "u8" keeps raw bytes. Bit-identical pixels and trajectories.
    pixel_format: str = "packed"
    # schedule
    epochs: int = 10
    steps: Optional[int] = None     # overrides epochs when set
    eval_every: int = 200           # steps between test-set evals
    target_accuracy: Optional[float] = 0.99  # early-stop when reached
    seed: int = 0
    # device / parallelism
    device: str = "auto"            # {auto, tpu, cpu}
    num_devices: Optional[int] = None  # None = all visible devices
    spmd_mode: str = "auto"         # {auto: jit+shardings, explicit: shard_map+psum}
    # tensor-parallel degree: folds devices into a ('data','model') mesh
    # and shards the dense stacks Megatron-style (parallel/tp.py).
    # Beyond-parity option; 1 = pure DP (the reference's strategy).
    model_parallel: int = 1
    dtype: str = "float32"          # compute dtype {float32, bfloat16}
    # steps fused into one XLA dispatch via lax.scan. MNIST steps are
    # ~100µs on TPU, so per-dispatch host overhead dominates at 1; a
    # scanned superstep amortizes it. None = auto (deep on TPU, 1 on CPU).
    steps_per_call: Optional[int] = None
    # checkpointing
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 500     # steps between async saves
    resume: bool = True             # restore latest checkpoint if present
    eval_only: bool = False         # restore + evaluate, no training
    # On SIGTERM (the warning real schedulers deliver before preempting a
    # worker), stop at the next block boundary and force-save a resumable
    # checkpoint instead of dropping progress since the last periodic
    # save. Only active when checkpoint_dir is set.
    graceful_preemption: bool = True
    # multi-host (config 5)
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # test/fault-injection hook (SURVEY.md §5 failure detection)
    fail_at_step: Optional[int] = None
    # dispatch pipelining: max steps in flight before blocking on the
    # oldest result. None = auto (deep on TPU to keep the pipeline full;
    # 1 on CPU, whose collective rendezvous deadlocks under concurrent
    # programs when the host thread pool is small).
    max_inflight: Optional[int] = None
    # observability
    profile_dir: Optional[str] = None  # jax.profiler trace output
    log_every: int = 100
    # gradient accumulation: microbatches per optimizer step (device-
    # resident pipeline only; one allreduce per step regardless)
    grad_accum: int = 1
    # ops
    fused_kernels: str = "auto"     # {auto, pallas, xla}: pallas fused MLP layer
    conv_impl: str = "auto"         # {auto, im2col, lax}: LeNet conv path
                                    # (auto: patch-matmul on TPU, lax on CPU)
    # serving (serve/, serve.py, bench.py serve): the dynamic batcher's
    # latency/throughput knobs. max_batch bounds rows per dispatch (and
    # the engine's top compile bucket); max_wait_us bounds how long the
    # oldest queued request may wait for coalescing; queue_depth is the
    # backpressure watermark in pending rows — beyond it submissions are
    # rejected with 503 semantics instead of melting latency.
    serve_max_batch: int = 512
    serve_max_wait_us: int = 1000
    serve_queue_depth: int = 4096
    # serving dispatch pipelining: max dispatched-but-unfetched batches
    # the batcher keeps in flight, so batch k's device compute overlaps
    # batch k+1's host staging and batch k-1's result fan-out — the
    # trainer's max_inflight discipline ported to serving. None = auto
    # (1 on CPU, where staging and compute share the same cores; a small
    # window on accelerators). 1 = the fully serial chain.
    serve_max_inflight: Optional[int] = None
    # adaptive batch scheduling (serve/scheduler.py): serve_slo_ms is
    # the per-request latency objective the AIMD controller defends —
    # observed violations step the effective coalescing wait down
    # (multiplicative), sustained headroom creeps it back up (additive),
    # always hard-capped at serve_max_wait_us. None = no SLO: the
    # controller is inert beyond its arrival-rate fill cap.
    # serve_adaptive=False (--no-adaptive) pins the static wait — the
    # escape hatch when the controller itself is suspected.
    serve_slo_ms: Optional[float] = None
    serve_adaptive: bool = True
    # model lifecycle (serve/registry.py): how many warmed versions the
    # registry keeps resident (live + rollback/candidate set). Each
    # resident version pins a full param set in device memory — the cap
    # bounds HBM cost; past it the oldest routeless version is evicted.
    serve_max_versions: int = 4
    # resilience (ISSUE 5, serve/resilience.py + serve/faults.py):
    # serve_bisect gates poison-batch isolation — a failed multi-request
    # dispatch is retried as recursively split halves so only the
    # culprit request fails. The circuit breaker demotes a live version
    # whose sliding window (serve_breaker_window_s seconds, at least
    # serve_breaker_min_requests of volume) crosses serve_breaker_ratio
    # failures, auto-promoting the newest healthy resident.
    # serve_faults installs a FaultInjector from a spec string
    # ("point:k=v,...;point2:..." — see serve/faults.py); None (the
    # default) leaves every woven failpoint inert.
    serve_bisect: bool = True
    serve_breaker_window_s: float = 5.0
    serve_breaker_min_requests: int = 20
    serve_breaker_ratio: float = 0.5
    serve_faults: Optional[str] = None
    # replica fleet (ISSUE 6, serve/fleet.py): serve_replicas > 1 puts
    # N engine replicas (mesh slices when the devices divide evenly,
    # logical replicas otherwise) behind the health-tracked
    # load-balancing dispatcher — per-replica in-flight windows of
    # serve_replica_inflight batches (None = the serve_max_inflight
    # auto rule, per replica), failover redispatch of a batch whose
    # replica dies, and (serve_hedge) hedged duplicates for batches
    # already past the p95 cost estimate. serve_retry_after_cap_s caps
    # the pipeline-derived Retry-After header on every shed response:
    # the derived value is unbounded when the in-flight window is deep
    # and a measured batch cost spikes, and RFC 9110 integer seconds
    # past ~30s just tell clients to go away.
    serve_replicas: int = 1
    serve_replica_inflight: Optional[int] = None
    serve_hedge: bool = False
    serve_retry_after_cap_s: float = 30.0
    # Request tracing (ISSUE 9, serve/trace.py): serve_trace installs
    # the per-request span tracer — GET /trace exports Chrome
    # trace-event JSON, /predict responses carry X-Trace-Id (and an
    # opt-in Server-Timing breakdown), and /metrics gains per-stage
    # duration histograms. serve_trace_sample head-samples which OK
    # traces are retained (errored and over-SLO requests are ALWAYS
    # kept — tail attribution is the point); serve_trace_capacity
    # bounds the retention ring. Default off: every woven span hook is
    # then one module-global None check.
    serve_trace: bool = False
    serve_trace_sample: float = 1.0
    serve_trace_capacity: int = 256
    # Inference fast path (ISSUE 7, serve/quantize.py): the serving
    # precision. "float32" runs the training-identical reference
    # forward; "bfloat16"/"int8" run the inference-specialized low-
    # precision path (int8 = per-output-channel weight quantization),
    # which only takes traffic after the registry's zero-compile
    # prove-it pass AND the accuracy-parity gate vs the f32 reference
    # (argmax agreement + relative logit diff, thresholds in PARITY.md).
    # "auto" warms+gates every variant and serves the cheapest
    # parity-passing one by the warmup-measured bucket cost tables.
    serve_infer_dtype: str = "float32"
    # Prediction cache + request dedup front layer (ISSUE 10,
    # serve/cache.py): serve_cache puts a bounded LRU response cache
    # keyed by (live version, infer_dtype, content hash of the input
    # bytes) in front of the batcher — repeats of a hot key are served
    # sub-millisecond with zero device work, concurrent identical
    # misses collapse onto ONE in-flight computation (single-flight),
    # and the registry invalidates atomically on promote/rollback/
    # dtype activation so a stale-version hit is impossible.
    # serve_cache_capacity bounds resident entries (LRU eviction past
    # it). serve_dedup additionally collapses identical rows INSIDE one
    # coalesced batcher drain (dispatch once, fan out — shrinks padded
    # buckets). Both default off: caching is a per-deployment choice
    # (it changes which requests ever reach the fault-injection
    # failpoints), and the Zipf bench leg measures the win explicitly.
    serve_cache: bool = False
    serve_cache_capacity: int = 4096
    serve_dedup: bool = False
    # Bounded staleness (ISSUE 14 satellite): entries older than
    # serve_cache_ttl_s (monotonic age) are expired at lookup — an
    # expired hit counts as a miss and recomputes. None = entries live
    # until LRU eviction or invalidation (the PR 10 behavior); models
    # are deterministic so TTLs exist for operational hygiene (bounding
    # how long any byte can possibly be served), not correctness.
    serve_cache_ttl_s: Optional[float] = None
    # Single-request low-latency fast lane (ISSUE 14, serve/batcher.py
    # + engine.dispatch_fast): a submit that finds the queue empty and
    # a free in-flight window slot dispatches immediately on the
    # caller's thread — no coalesce timer, no queue hand-offs — with
    # device-resident staging for small buckets and fallback to the
    # coalescing path the moment contention appears. Off by default:
    # the lane trades a little peak coalescing opportunity for idle
    # p50, which is a per-deployment choice (the --lowlat bench leg
    # measures it).
    serve_fastlane: bool = False
    # Confidence-gated model cascade (ISSUE 17, serve/cascade.py):
    # serve_cascade fronts the pipeline with a two-stage dispatcher —
    # the cheap parity-gated variant (int8 by default) answers every
    # row whose softmax margin clears a confidence threshold calibrated
    # on the held-out parity batch; uncertain rows escalate to the f32
    # reference through the normal coalescing path. The cascade only
    # takes traffic after an END-TO-END composed-accuracy gate (the
    # cascade's answers must match f32 within the PARITY.md bar).
    # serve_cascade_threshold overrides the calibrated threshold (same
    # gate judges the override; a failing override refuses loudly).
    serve_cascade: bool = False
    serve_cascade_threshold: Optional[float] = None
    # Multi-tenant, multi-model serving (ISSUE 18, serve/tenancy.py):
    # serve_models lists the catalog ("mlp,lenet" boots BOTH models,
    # each with its own registry/router/batcher and checkpoint subtree
    # <checkpoint_dir>/<model>; empty = just cfg.model, the single-
    # model compatibility path). serve_tenants configures the admission
    # classes the X-Tenant header maps to —
    # "name:qps=50,burst=25,deadline_ms=50,weight=1,model=mlp;..." —
    # token-bucket quota (429 + Retry-After past it), per-class default
    # deadline (infeasible heads shed 504 by the cost model), and the
    # weighted-fair scheduling weight. Empty = tenancy layer off.
    # serve_tenant_quantum_us is the deficit-round-robin credit each
    # ring visit grants per unit weight, in microseconds of MODELED
    # dispatch cost: smaller interleaves tenants more finely, larger
    # amortizes scheduling over longer per-tenant runs.
    serve_models: str = ""
    serve_tenants: str = ""
    serve_tenant_quantum_us: float = 5000.0
    # Horizontal scale-out gateway (ISSUE 19, serve/gateway.py):
    # gateway_workers > 0 turns `serve.py --port P` into a front-door
    # process that spawns that many full serve.py workers and routes
    # /predict across them on a consistent-hash ring keyed like the
    # prediction cache (hot keys shard across workers instead of
    # duplicating). gateway_worker_inflight is the per-worker
    # in-flight window the gateway will queue before shedding 503
    # (backpressure that composes UNDER tenant admission);
    # gateway_vnodes is the ring's virtual-node count per worker
    # (more = smoother key spread, marginally slower membership ops).
    gateway_workers: int = 0
    gateway_worker_inflight: int = 8
    gateway_vnodes: int = 64
    # Closed-loop autoscaling (ISSUE 20, serve/autoscale.py):
    # serve_autoscale runs the hysteresis controller over the live
    # saturation surface (queue watermark, in-flight depth, shed
    # deltas, traced queue-wait p99 vs serve_slo_ms) and actuates ONE
    # narrow interface — the batcher's in-flight window + bucket
    # ceiling on a single host, worker spawn/drain under a gateway.
    # Floor/ceiling are HARD bounds in actuator units; a tick that
    # wants past the ceiling is disclosed as saturation, never acted.
    # high/low are the hysteresis bands on the normalized pressure
    # signal (grow at >= high, shrink at <= low, dead zone between);
    # cooldown_s suppresses any action inside the window after one
    # (the anti-flap guarantee); interval_s is the control tick.
    serve_autoscale: bool = False
    serve_autoscale_floor: int = 1
    serve_autoscale_ceiling: Optional[int] = None
    serve_autoscale_interval_s: float = 0.25
    serve_autoscale_cooldown_s: float = 2.0
    serve_autoscale_high: float = 0.75
    serve_autoscale_low: float = 0.25
    # Flatten params/grads/moments into one contiguous vector inside the
    # optimizer update (optax.flatten): one fused elementwise update over
    # 61k/101k params instead of dozens of tiny per-leaf ops — measured
    # 0.15 ms/step faster at batch 512 (scripts/profile_step.py).
    # Bit-identical trajectories. Auto-disabled under model_parallel > 1
    # (TP shards optimizer moments by leaf name; a flat vector can't be).
    flat_optimizer: bool = True

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


# BASELINE.json configs[0..4] as named presets.
PRESETS: dict[str, Config] = {
    # config 1: single-process 2-layer MLP (784-128-10) on MNIST, SGD, batch=64
    "mlp-sgd": Config(model="mlp", optimizer="sgd", learning_rate=0.1,
                      batch_size=64, num_devices=1),
    # config 2: single-process LeNet-5 CNN on MNIST, Adam
    "lenet-adam": Config(model="lenet", optimizer="adam", learning_rate=1e-3,
                         num_devices=1, batch_size=128),
    # config 3: 2-worker data-parallel MLP with gradient allreduce
    "mlp-dp2": Config(model="mlp", optimizer="sgd", learning_rate=0.1,
                      batch_size=128, num_devices=2),
    # config 4: 8-chip data-parallel LeNet-5, per-rank sharding, batch=512
    "lenet-dp8": Config(model="lenet", optimizer="adam", learning_rate=1e-3,
                        batch_size=512, num_devices=8),
    # config 5: multi-host data-parallel LeNet-5 with async checkpoint/restore
    # (coordinator/num_processes/process_id supplied on the command line)
    "lenet-multihost": Config(model="lenet", optimizer="adam",
                              learning_rate=1e-3, batch_size=512,
                              checkpoint_dir="checkpoints",
                              checkpoint_every=200),
}


def add_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    p.add_argument("--preset", choices=sorted(PRESETS), default=None,
                   help="named workload preset (BASELINE.json configs 1-5)")
    p.add_argument("--model", choices=["mlp", "lenet"], default=None)
    p.add_argument("--optimizer", choices=["sgd", "adam"], default=None)
    p.add_argument("--learning-rate", type=float, default=None)
    p.add_argument("--momentum", type=float, default=None)
    p.add_argument("--lr-schedule",
                   choices=["constant", "cosine", "warmup-cosine"],
                   default=None)
    p.add_argument("--warmup-steps", type=int, default=None)
    p.add_argument("--lr-decay-steps", type=int, default=None,
                   help="pin the cosine decay horizon (steps); default "
                        "is the run's own total step count")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--synthetic", action="store_true", default=None)
    p.add_argument("--data-pipeline", choices=["device", "stream"],
                   default=None)
    p.add_argument("--stream-source", choices=["numpy", "tfdata"],
                   default=None)
    p.add_argument("--pixel-format", choices=["packed", "u8"],
                   default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--eval-every", type=int, default=None)
    p.add_argument("--target-accuracy", type=float, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--device", choices=["auto", "tpu", "cpu"], default=None)
    p.add_argument("--num-devices", type=int, default=None)
    p.add_argument("--spmd-mode", choices=["auto", "explicit"], default=None)
    p.add_argument("--steps-per-call", type=int, default=None)
    p.add_argument("--model-parallel", type=int, default=None)
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--no-resume", dest="resume", action="store_false",
                   default=None)
    p.add_argument("--no-graceful-preemption", dest="graceful_preemption",
                   action="store_false", default=None,
                   help="don't catch SIGTERM to force-save a checkpoint "
                        "before exiting")
    p.add_argument("--eval-only", dest="eval_only", action="store_true",
                   default=None,
                   help="restore from --checkpoint-dir and evaluate; "
                        "no training steps")
    p.add_argument("--coordinator-address", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--fail-at-step", type=int, default=None)
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--log-every", type=int, default=None)
    p.add_argument("--fused-kernels", choices=["auto", "pallas", "xla"],
                   default=None)
    p.add_argument("--conv-impl", choices=["auto", "im2col", "lax"],
                   default=None)
    p.add_argument("--grad-accum", type=int, default=None,
                   help="microbatches accumulated per optimizer step")
    p.add_argument("--serve-max-batch", type=int, default=None,
                   help="[serving] max rows per inference dispatch (also "
                        "the engine's top compile bucket)")
    p.add_argument("--serve-max-wait-us", type=int, default=None,
                   help="[serving] max microseconds the oldest queued "
                        "request waits for batch coalescing")
    p.add_argument("--serve-queue-depth", type=int, default=None,
                   help="[serving] backpressure watermark in pending "
                        "rows; beyond it requests are rejected (503)")
    p.add_argument("--serve-max-inflight", type=int, default=None,
                   help="[serving] max dispatched-but-unfetched batches "
                        "kept in flight (pipelined dispatch; default: "
                        "1 on cpu, 4 on accelerators)")
    p.add_argument("--serve-slo-ms", type=float, default=None,
                   help="[serving] per-request latency SLO in ms: the "
                        "adaptive controller steps the effective "
                        "coalescing wait down on violations and back up "
                        "under headroom (hard cap: --serve-max-wait-us)")
    p.add_argument("--no-adaptive", dest="serve_adaptive",
                   action="store_false", default=None,
                   help="[serving] pin the static coalescing wait "
                        "instead of the SLO-aware adaptive controller")
    p.add_argument("--serve-max-versions", type=int, default=None,
                   help="[serving] warmed model versions kept resident "
                        "in the registry (live + rollback/candidates); "
                        "each pins one param set in device memory")
    p.add_argument("--no-bisect", dest="serve_bisect",
                   action="store_false", default=None,
                   help="[serving] fail a whole batch on a dispatch "
                        "error instead of bisecting it to isolate the "
                        "poison request")
    p.add_argument("--serve-breaker-window-s", type=float, default=None,
                   help="[serving] circuit-breaker sliding window in "
                        "seconds over per-version request outcomes")
    p.add_argument("--serve-breaker-min-requests", type=int, default=None,
                   help="[serving] minimum window volume before the "
                        "breaker may trip (no tripping on one bad "
                        "request at 3am)")
    p.add_argument("--serve-breaker-ratio", type=float, default=None,
                   help="[serving] failure ratio within the window that "
                        "trips the breaker and auto-rolls the live "
                        "version back (0 < ratio <= 1)")
    p.add_argument("--serve-faults", default=None, metavar="SPEC",
                   help="[serving] install a fault-injection schedule "
                        "(serve/faults.py spec string, e.g. "
                        "'engine.fetch:p=0.01,latency_ms=5'); chaos "
                        "testing only — default: all failpoints inert")
    p.add_argument("--serve-replicas", type=int, default=None,
                   help="[serving] engine replicas behind the "
                        "load-balancing fleet dispatcher (mesh slices "
                        "when devices divide evenly, logical replicas "
                        "otherwise); 1 = the single-engine path")
    p.add_argument("--serve-replica-inflight", type=int, default=None,
                   help="[serving] per-replica bounded in-flight window "
                        "in batches (default: the serve-max-inflight "
                        "auto rule, applied per replica)")
    p.add_argument("--serve-hedge", dest="serve_hedge",
                   action="store_true", default=None,
                   help="[serving] hedge batches already past the p95 "
                        "cost estimate with a duplicate dispatch on a "
                        "free healthy sibling replica (first result "
                        "wins)")
    p.add_argument("--serve-infer-dtype",
                   choices=["float32", "bfloat16", "int8", "megakernel",
                            "auto"],
                   default=None,
                   help="[serving] inference precision: float32 = the "
                        "training-identical reference forward; "
                        "bfloat16/int8 = the quantized+fused fast path "
                        "(takes traffic only after the zero-compile "
                        "prove-it pass AND the accuracy-parity gate); "
                        "megakernel = the f32 whole-net fused-inference "
                        "variant (MLP only, one Pallas call per "
                        "dispatch, same two gates); "
                        "auto = cheapest parity-passing variant by the "
                        "warmup cost tables")
    p.add_argument("--serve-cache", dest="serve_cache",
                   action="store_true", default=None,
                   help="[serving] enable the prediction cache +"
                        " single-flight front layer (serve/cache.py):"
                        " content-hash repeats served without device"
                        " work, concurrent identical misses collapsed"
                        " onto one computation, invalidated atomically"
                        " on promote/rollback/dtype activation")
    p.add_argument("--serve-cache-capacity", type=int, default=None,
                   help="[serving] bounded prediction-cache size in "
                        "entries (LRU eviction past it; default 4096)")
    p.add_argument("--serve-dedup", dest="serve_dedup",
                   action="store_true", default=None,
                   help="[serving] collapse identical rows inside one "
                        "coalesced batcher drain into a single "
                        "dispatch (intra-batch dedup — shrinks padded "
                        "buckets on hot-key traffic)")
    p.add_argument("--serve-cache-ttl-s", type=float, default=None,
                   help="[serving] bounded staleness for the prediction "
                        "cache: entries older than this many seconds "
                        "(monotonic age) expire at lookup — an expired "
                        "hit counts as a miss and recomputes (default: "
                        "no TTL; entries live until LRU eviction or a "
                        "route-change invalidation)")
    p.add_argument("--serve-fastlane", dest="serve_fastlane",
                   action="store_true", default=None,
                   help="[serving] single-request low-latency bypass "
                        "lane: a submit that finds the queue empty and "
                        "a free in-flight slot dispatches immediately "
                        "on the caller's thread (no coalesce timer, no "
                        "queue hand-offs, device-resident staging for "
                        "small buckets); contention falls back to the "
                        "coalescing path")
    p.add_argument("--serve-cascade", dest="serve_cascade",
                   action="store_true", default=None,
                   help="[serving] confidence-gated model cascade "
                        "(serve/cascade.py): the cheap parity-gated "
                        "variant answers rows whose softmax margin "
                        "clears a calibrated confidence threshold; "
                        "uncertain rows escalate to the f32 reference. "
                        "Promotable only after the end-to-end composed-"
                        "accuracy gate passes (PARITY.md). Per-request "
                        "X-Accuracy-Class picks fast|balanced|exact")
    p.add_argument("--serve-cascade-threshold", type=float, default=None,
                   help="[serving] override the calibrated cascade "
                        "confidence threshold (margin in [0, 1]; rows "
                        "below it escalate). The composed-accuracy "
                        "gate still judges the override — a failing "
                        "value refuses the cascade loudly")
    p.add_argument("--serve-models", default=None,
                   help="[serving] comma-separated model catalog "
                        "(serve/tenancy.py): 'mlp,lenet' serves BOTH "
                        "models from one process, each with its own "
                        "registry, bucket geometry, cost tables and "
                        "checkpoint subtree <checkpoint-dir>/<model>. "
                        "Empty (default) serves --model alone")
    p.add_argument("--serve-tenants", default=None,
                   help="[serving] tenant SLO classes for the X-Tenant "
                        "header, 'name:qps=50,burst=25,deadline_ms=50,"
                        "weight=1,model=mlp;name2:...' — token-bucket "
                        "quota (429 + Retry-After on breach), default "
                        "deadline (infeasible requests shed 504), and "
                        "weighted-fair-queueing weight. Setting this "
                        "routes /predict through the global scheduler "
                        "(GET /tenants, POST /tenants/{id}/quota)")
    p.add_argument("--serve-tenant-quantum-us", type=float, default=None,
                   help="[serving] deficit-round-robin quantum: modeled "
                        "dispatch microseconds credited per ring visit "
                        "per unit tenant weight")
    p.add_argument("--gateway", dest="gateway_workers", type=int,
                   default=None, metavar="N",
                   help="[serving] horizontal scale-out (serve/"
                        "gateway.py): become a front-door process over "
                        "N spawned serve.py workers — /predict routes "
                        "on a consistent-hash ring keyed like the "
                        "prediction cache (hot keys shard, not "
                        "duplicate), promote fans out two-phase under "
                        "a cluster epoch. Every other serving flag "
                        "forwards to the workers verbatim")
    p.add_argument("--gateway-worker-inflight", type=int, default=None,
                   help="[serving] per-worker in-flight window at the "
                        "gateway; a full window sheds 503 instead of "
                        "spilling an affinity key to a sibling cache")
    p.add_argument("--gateway-vnodes", type=int, default=None,
                   help="[serving] virtual nodes per worker on the "
                        "consistent-hash ring (more = smoother key "
                        "spread)")
    p.add_argument("--serve-autoscale", dest="serve_autoscale",
                   action="store_true", default=None,
                   help="[serving] run the closed-loop autoscaler "
                        "(serve/autoscale.py): a hysteresis controller "
                        "over queue watermark / in-flight depth / shed "
                        "deltas / traced p99 that widens or narrows the "
                        "batcher's in-flight window + bucket ceiling "
                        "(single host) or spawns/drains workers (under "
                        "--gateway), with cooldown anti-flap and hard "
                        "floor/ceiling bounds")
    p.add_argument("--serve-autoscale-floor", type=int, default=None,
                   help="[serving] hard autoscale floor in actuator "
                        "units (window slots or workers; default 1)")
    p.add_argument("--serve-autoscale-ceiling", type=int, default=None,
                   help="[serving] hard autoscale ceiling in actuator "
                        "units (default: the actuator's natural bound "
                        "— the constructed in-flight window, or "
                        "2x the initial worker count)")
    p.add_argument("--serve-autoscale-interval-s", type=float,
                   default=None,
                   help="[serving] autoscaler control-tick period in "
                        "seconds (default 0.25)")
    p.add_argument("--serve-autoscale-cooldown-s", type=float,
                   default=None,
                   help="[serving] minimum seconds between actuated "
                        "scale decisions; any decision inside the "
                        "window is suppressed and counted (default 2)")
    p.add_argument("--serve-autoscale-high", type=float, default=None,
                   help="[serving] grow when normalized pressure >= "
                        "this hysteresis band (default 0.75)")
    p.add_argument("--serve-autoscale-low", type=float, default=None,
                   help="[serving] shrink when normalized pressure <= "
                        "this band (default 0.25); must be < high")
    p.add_argument("--serve-retry-after-cap-s", type=float, default=None,
                   help="[serving] ceiling on the pipeline-derived "
                        "Retry-After header (integer seconds per "
                        "RFC 9110) on shed responses")
    p.add_argument("--serve-trace", dest="serve_trace",
                   action="store_true", default=None,
                   help="[serving] per-request span tracing: GET "
                        "/trace exports Chrome trace-event JSON, "
                        "/predict responses carry X-Trace-Id, /metrics "
                        "gains per-stage duration histograms (errored "
                        "and over-SLO traces always retained)")
    p.add_argument("--serve-trace-sample", type=float, default=None,
                   help="[serving] head-sampling fraction for OK "
                        "traces in the retention ring (exemplars are "
                        "never sampled out); default 1.0")
    p.add_argument("--serve-trace-capacity", type=int, default=None,
                   help="[serving] bounded retention ring size in "
                        "traces (default 256)")
    p.add_argument("--no-flat-optimizer", dest="flat_optimizer",
                   action="store_false", default=None,
                   help="per-leaf optimizer update instead of the fused "
                        "flat-vector update")
    p.add_argument("--flat-optimizer", dest="flat_optimizer",
                   action="store_true", default=None,
                   help="force the fused flat-vector update (the default; "
                        "the explicit flag exists to restore checkpoints "
                        "written with it after --no-flat-optimizer runs)")
    return p


def from_args(args: argparse.Namespace) -> Config:
    cfg = PRESETS[args.preset] if args.preset else Config()
    overrides = {}
    for f in dataclasses.fields(Config):
        v = getattr(args, f.name, None)
        if v is not None:
            overrides[f.name] = v
    return cfg.replace(**overrides)
