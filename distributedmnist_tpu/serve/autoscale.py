"""Closed-loop autoscaling over the serving stack (ISSUE 20).

Clipper-style adaptive serving and Clockwork's predictability-first
resource decisions (PAPERS.md) both presuppose a controller that reacts
to load; the repo already emits every input such a controller needs —
the PR 9 saturation surface (queue watermark, in-flight depth,
per-stage p99 from the tracer, shed counters) and the PR 4 warmup-
priced cost tables — but until this module nothing closed the loop.

The **Autoscaler** is a control thread that reads the live saturation
surface each tick and actuates through ONE narrow interface:

    Actuator.scale_to(units) -> achieved units

with exactly two implementations —

    WindowActuator    single host: units widen/narrow the batcher's
                      in-flight window AND walk its coalescing bucket
                      ceiling along the engine's PRE-WARMED bucket
                      ladder (bigger batches amortize dispatch overhead
                      at zero new jit keys — scale-up never recompiles)
    GatewayActuator   fleet: units spawn/drain whole gateway workers
                      (PR 19) — grow joins a freshly spawned worker to
                      the ring, shrink ring-exits + drains one

Control discipline (the flap-prevention contract the bench asserts):

- **hysteresis bands**: grow only above the `high` pressure watermark,
  shrink only below `low` — the dead band between them absorbs noise.
- **cooldown**: after any action, further actions are suppressed for
  `cooldown_s` (counted + exported) — a grow can never be immediately
  reversed by a shrink inside one window, so the zero-flap acceptance
  bar holds by construction, not by tuning.
- **floor/ceiling**: hard bounds from config, enforced at decision
  time AND inside both actuators (a bug in one layer cannot scale to
  zero or past the provisioned ceiling). A tick that wants to grow
  past the ceiling marks `saturated` on its decision — the disclosed
  "ceiling hit" state the bench and README surface.
- **cost-model pricing**: every action is priced before it is taken —
  chip-seconds/second bought (the reserved-capacity delta, on the
  actuator's disclosed `cost_basis`) against the predicted capacity
  gain in rows/s from the warmup-measured bucket-cost affine fit. The
  price rides the action record and the
  `dmnist_serve_autoscale_last_cost_chip_seconds` gauge.

Pressure is the max of the normalized saturation signals:
queue_frac (pending rows / backpressure watermark), inflight_frac
(in-flight batches / live window), a shed spike (any rejection since
the last tick pins pressure to 1.0 — shedding IS saturation), and the
SLO ratio (p99/SLO, scaled so a breach alone clears the high band).

Lint DML019 fences the actuation surface: `apply_scale` /
`add_worker` / `drain_worker` calls outside Actuator.scale_to are
findings — a second writer would race this loop's decisions and
un-price its accounting. All primitives come from analysis/locks.py
(sanitizer + schedule-explorer instrumented; the `autoscaler-loop`
machine explores this loop against load spikes, a mid-decision worker
death, and racing stop()).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional

from distributedmnist_tpu.analysis.locks import (make_condition,
                                                 make_lock, make_thread)

log = logging.getLogger("serve.autoscale")


@dataclasses.dataclass(frozen=True)
class Signals:
    """One tick's saturation surface. Every field is already
    normalized or absolute — the Autoscaler does no I/O itself, so a
    fake signal source makes the whole loop explorable/testable."""

    queue_frac: float                  # pending rows / queue watermark
    inflight_frac: float               # in-flight batches / live window
    shed_delta: int                    # rejections since previous tick
    p99_ms: Optional[float] = None     # stage/end-to-end p99 if known
    slo_ms: Optional[float] = None     # the objective p99 is judged by

    def pressure(self) -> float:
        p = max(self.queue_frac, self.inflight_frac)
        if self.shed_delta > 0:
            p = max(p, 1.0)            # shedding IS saturation
        if self.p99_ms is not None and self.slo_ms:
            # scaled so p99 == SLO reads 1.0 — a breach alone must
            # clear any sane high watermark
            p = max(p, self.p99_ms / self.slo_ms)
        return p


def batcher_signals(batcher, metrics=None,
                    slo_ms: Optional[float] = None,
                    tracer=None) -> Callable[[], Signals]:
    """The single-host signal source: a closure over the live batcher
    (+ optional ServeMetrics shed counter and tracer queue-wait p99).
    Holds no locks across reads — each accessor locks internally."""
    last_rejected = [metrics.rejected_total() if metrics is not None
                     else 0]

    def read() -> Signals:
        pending = batcher.pending_rows()
        depth = batcher.inflight_batches()
        window = max(batcher.window(), 1)
        shed = 0
        if metrics is not None:
            total = metrics.rejected_total()
            shed = total - last_rejected[0]
            last_rejected[0] = total
        p99 = None
        if tracer is not None:
            p99 = tracer.stage_p99_ms("queue.wait")
        return Signals(
            queue_frac=pending / max(batcher.queue_depth, 1),
            inflight_frac=depth / window,
            shed_delta=shed, p99_ms=p99, slo_ms=slo_ms)

    return read


# -- actuators -------------------------------------------------------------


class WindowActuator:
    """Single-host actuation: unit u maps to (in-flight window u,
    bucket ceiling u-1 rungs above the base bucket) — both sides of
    the same capacity knob, moved together through the batcher's ONE
    actuation surface. Every rung is a bucket the engine warmed at
    boot, so scaling never compiles (the recompiles_after_warmup==0
    bar survives autoscaling by construction).

    chip-second accounting (`cost_basis`): units are reserved in-flight
    window slots on ONE chip — slot-seconds, not extra silicon. The
    gateway actuator's basis is worker-chip-seconds (real chips); the
    bench discloses whichever basis priced its record.
    """

    kind = "window"
    cost_basis = "inflight-window-slot-seconds"

    def __init__(self, batcher, floor: int, ceiling: int,
                 base_max_batch: Optional[int] = None):
        if not 1 <= floor <= ceiling:
            raise ValueError(
                f"need 1 <= floor <= ceiling, got [{floor}, {ceiling}]")
        self._batcher = batcher
        self.floor = floor
        self.ceiling = min(ceiling, batcher.max_inflight)
        buckets = list(batcher.engine.buckets)
        base = base_max_batch or batcher.max_batch
        base_idx = next((i for i, b in enumerate(buckets) if b >= base),
                        len(buckets) - 1)
        # unit u's bucket ceiling: u - floor rungs above the base,
        # clamped to the warmed ladder top
        self._plan = {
            u: (u, buckets[min(base_idx + (u - self.floor),
                               len(buckets) - 1)])
            for u in range(1, self.ceiling + 1)}
        self._units = min(max(self._current_window(), self.floor),
                          self.ceiling)

    def _current_window(self) -> int:
        return self._batcher.window()

    def current(self) -> int:
        return self._units

    def plan(self, units: int) -> tuple:
        u = min(max(units, 1), self.ceiling)
        return self._plan[u]

    def scale_to(self, units: int) -> int:
        """Apply unit target through the batcher's actuation surface;
        returns the ACHIEVED units (narrowing can be partial while the
        pipeline is full — the next tick retries)."""
        u = min(max(units, self.floor), self.ceiling)
        window, max_batch = self._plan[u]
        got = self._batcher.apply_scale(window=window,
                                        max_batch=max_batch)
        # achieved units: the window actually reached (bucket ceiling
        # always applies — it is a lock-guarded assignment)
        self._units = min(max(got["window"], 1), self.ceiling)
        return self._units

    def capacity_rows_per_s(self, units: int) -> Optional[float]:
        """Predicted steady-state capacity at `units` from the warmup
        cost table: the unit's bucket ceiling amortized over its fitted
        dispatch cost. None before the table is complete (pricing then
        reports unknown instead of a guess)."""
        from distributedmnist_tpu.serve.scheduler import (
            estimate_dispatch_s)
        engine = self._batcher.engine
        costs = engine.bucket_costs()
        buckets = list(engine.buckets)
        if not costs or not all(b in costs for b in buckets):
            return None
        _, bucket = self.plan(units)
        cost = estimate_dispatch_s(bucket, buckets, costs)
        if cost <= 0:
            return None
        return bucket / cost

    def chip_fraction(self, units: int) -> float:
        return float(min(max(units, 1), self.ceiling))

    def close(self) -> None:
        pass                    # batcher.stop() unparks any held permits


class GatewayActuator:
    """Fleet actuation (PR 19): unit u = u active gateway workers.
    Grow spawns a fresh serve.py worker (the gateway's own argv via
    worker_argv) and joins it to the ring; shrink ring-exits + drains
    the youngest autoscaled worker and terminates its process. The
    spawn/drain callables are injectable so unit tests actuate
    in-memory fakes instead of subprocesses."""

    kind = "gateway"
    cost_basis = "worker-chip-seconds"

    def __init__(self, gateway, floor: int, ceiling: int,
                 spawn: Optional[Callable] = None,
                 terminate: Optional[Callable] = None,
                 per_worker_rows_per_s: Optional[float] = None):
        if not 1 <= floor <= ceiling:
            raise ValueError(
                f"need 1 <= floor <= ceiling, got [{floor}, {ceiling}]")
        self._gateway = gateway
        self.floor = floor
        self.ceiling = ceiling
        self._spawn = spawn
        self._terminate = terminate or _terminate_worker
        self._seq = 0
        self._grown: list = []          # rids this actuator added, LIFO
        self._per_worker = per_worker_rows_per_s

    def current(self) -> int:
        return len(self._gateway._active())

    def scale_to(self, units: int) -> int:
        u = min(max(units, self.floor), self.ceiling)
        while self.current() < u:
            self._seq += 1
            rid = f"as{self._seq}"
            worker = self._spawn(rid)   # may raise: loop reports + retries
            self._gateway.add_worker(worker)
            self._grown.append(rid)
        while self.current() > u:
            # drain the youngest autoscaled worker first; never a
            # boot-time member unless the actuator grew none
            rid = (self._grown.pop() if self._grown else
                   self._gateway._active()[-1].rid)
            worker = self._gateway.drain_worker(rid)
            self._terminate(worker)
        return self.current()

    def capacity_rows_per_s(self, units: int) -> Optional[float]:
        if self._per_worker is None:
            return None
        return self._per_worker * min(max(units, 1), self.ceiling)

    def chip_fraction(self, units: int) -> float:
        return float(min(max(units, 1), self.ceiling))

    def close(self) -> None:
        pass


def _terminate_worker(worker) -> None:
    try:
        worker.transport.close()
    except Exception:
        pass
    if getattr(worker, "proc", None) is not None:
        worker.proc.terminate()


# -- the control loop ------------------------------------------------------


class Autoscaler:
    """The closed control loop: read Signals, decide against the
    hysteresis bands, price the step, actuate — one action per tick at
    most, never inside the cooldown window. `tick()` is public and
    synchronous (tests and the schedule explorer drive it directly);
    `start()` runs it on a named daemon thread every `interval_s`.

    Thread-safety: decisions + actuation serialize on one admin lock
    (blocking_ok — GatewayActuator spawns processes under it BY
    DESIGN; nothing on the request path ever takes it), so a manual
    tick() racing the loop thread can never double-actuate. stop()
    wakes and joins the loop; a stop() landing mid-decision waits for
    that decision to finish rather than abandoning a half-applied
    scale."""

    def __init__(self, actuator, signals: Callable[[], Signals], *,
                 floor: Optional[int] = None,
                 ceiling: Optional[int] = None,
                 high: float = 0.75, low: float = 0.25,
                 cooldown_s: float = 2.0, interval_s: float = 0.25,
                 metrics=None):
        if not 0.0 <= low < high:
            raise ValueError(
                f"need 0 <= low < high, got low={low} high={high}")
        if cooldown_s < 0 or interval_s <= 0:
            raise ValueError("cooldown_s must be >= 0 and "
                             "interval_s > 0")
        self.actuator = actuator
        self._signals = signals
        self.floor = max(floor if floor is not None else actuator.floor,
                         actuator.floor)
        self.ceiling = min(ceiling if ceiling is not None
                           else actuator.ceiling, actuator.ceiling)
        if self.floor > self.ceiling:
            raise ValueError(
                f"floor {self.floor} exceeds ceiling {self.ceiling}")
        self.high = high
        self.low = low
        self.cooldown_s = cooldown_s
        self.interval_s = interval_s
        self.metrics = metrics
        self._cond = make_condition("autoscale.tick")
        self._act_lock = make_lock("autoscale.admin", blocking_ok=True)
        self._stop = False
        self._thread = None
        self._t0 = time.monotonic()
        self._last_action_t: Optional[float] = None
        # action log: one dict per APPLIED action (the bench's flap
        # audit + the artifact's scale_actions record). Guarded by
        # _act_lock — appended only inside tick().
        self.actions: list = []
        self.suppressed = 0             # cooldown-suppressed decisions
        self.errors = 0                 # actuation failures (retried)
        self.saturated_ticks = 0        # grow wanted past the ceiling
        if self.metrics is not None:
            self.metrics.record_autoscale_scale(actuator.current())

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._thread = make_thread(target=self._loop,
                                   name="serve-autoscale", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
        self._thread = None
        self.actuator.close()

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                self._cond.wait(self.interval_s)
                if self._stop:
                    return
            try:
                self.tick()
            except Exception:
                # a torn signal source or actuator must never kill the
                # loop — the next tick re-reads fresh state
                self.errors += 1
                log.exception("autoscale tick failed; retrying")

    # -- one decision ------------------------------------------------------

    def tick(self) -> Optional[dict]:
        """One read-decide-price-actuate cycle. Returns the applied
        action record, or None (in band / cooldown / at a bound /
        actuation failed)."""
        with self._act_lock:
            sig = self._signals()
            pressure = sig.pressure()
            cur = self.actuator.current()
            if pressure >= self.high:
                target = cur + 1
            elif pressure <= self.low:
                target = cur - 1
            else:
                return None
            if target > self.ceiling:
                # ceiling hit: disclosed saturation, not silent clamping
                self.saturated_ticks += 1
                if self.metrics is not None:
                    self.metrics.record_autoscale_saturated()
                return None
            if target < self.floor:
                return None
            now = time.monotonic()
            if (self._last_action_t is not None
                    and now - self._last_action_t < self.cooldown_s):
                self.suppressed += 1
                if self.metrics is not None:
                    self.metrics.record_autoscale_suppressed()
                return None
            direction = "grow" if target > cur else "shrink"
            # price BEFORE actuating: chip-seconds/second bought vs the
            # cost model's predicted capacity delta
            price = (self.actuator.chip_fraction(target)
                     - self.actuator.chip_fraction(cur))
            cap_cur = self.actuator.capacity_rows_per_s(cur)
            cap_new = self.actuator.capacity_rows_per_s(target)
            gain = (cap_new - cap_cur
                    if cap_cur is not None and cap_new is not None
                    else None)
            try:
                achieved = self.actuator.scale_to(target)
            except Exception as e:
                # mid-decision actuator death (a worker that died while
                # being drained/joined): count, keep the loop alive —
                # the next tick re-reads the real fleet state
                self.errors += 1
                log.warning("autoscale %s %d -> %d failed: %s",
                            direction, cur, target, e)
                return None
            self._last_action_t = now
            action = {
                "t_s": round(now - self._t0, 4),
                "direction": direction,
                "from_units": cur, "to_units": target,
                "achieved_units": achieved,
                "pressure": round(pressure, 4),
                "price_chip_s_per_s": price,
                "predicted_gain_rows_per_s":
                    round(gain, 2) if gain is not None else None,
                "cost_basis": self.actuator.cost_basis,
            }
            self.actions.append(action)
            if self.metrics is not None:
                self.metrics.record_autoscale_action(
                    direction, achieved, price)
            return action

    # -- reporting ---------------------------------------------------------

    def describe(self) -> dict:
        with self._act_lock:
            return {
                "actuator": self.actuator.kind,
                "cost_basis": self.actuator.cost_basis,
                "floor": self.floor, "ceiling": self.ceiling,
                "high": self.high, "low": self.low,
                "cooldown_s": self.cooldown_s,
                "interval_s": self.interval_s,
                "scale": self.actuator.current(),
                "actions": list(self.actions),
                "suppressed": self.suppressed,
                "errors": self.errors,
                "saturated_ticks": self.saturated_ticks,
            }

    def flaps(self, cooldown_s: Optional[float] = None) -> int:
        """Grow-immediately-reversed-by-shrink pairs inside one
        cooldown window (either order) — the acceptance bar counts
        ZERO of these. Computed from the action log so the artifact's
        claim is auditable, not asserted."""
        win = cooldown_s if cooldown_s is not None else self.cooldown_s
        n = 0
        with self._act_lock:
            acts = list(self.actions)
        for a, b in zip(acts, acts[1:]):
            if (a["direction"] != b["direction"]
                    and b["t_s"] - a["t_s"] < win):
                n += 1
        return n
