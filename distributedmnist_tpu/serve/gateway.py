"""Horizontal scale-out gateway (ISSUE 19): a consistent-hash front
door over N `serve.py` worker processes, with a sharded prediction
cache and cluster-epoch coordinated promote.

Every scaling layer before this PR lived inside ONE process: the
replica fleet (ISSUE 6) multiplies engines, the prediction cache
(ISSUE 10) multiplies goodput on hot keys, the tenant scheduler
(ISSUE 18) multiplexes models — all behind a single HTTP listener on a
single Python runtime. This module is the process-level half the
ROADMAP calls the missing piece: a gateway process that owns the
public port and routes across a fleet of full serve.py stacks (each
its own registry/batcher/engine over shared checkpoint storage), the
way Clipper fronts heterogeneous model containers with one routing
layer (PAPERS.md).

Routing policy — shard, don't duplicate:

- **Consistent-hash affinity for cacheable traffic.** The ring is
  keyed by the same `(live version, infer dtype, rows, sha256(body))`
  identity the PR 10 cache keys entries by (`cache.content_key`), so
  every repeat of a hot key lands on the SAME worker and the fleet's
  aggregate cache holds each entry exactly once — N workers buy N
  distinct cache shards, not N copies of the hottest shard. A miss
  routed off-ring would compute AND insert the entry on a non-owner
  (a duplicate by construction), so affinity is the policy for every
  keyable request; the gateway never speculates on per-key hit state.
- **Cost-aware least-loaded fallback** for everything that cannot hit
  a cache: requests with no computable route identity (no live
  version yet, affinity disabled because the fleet runs uncached) and
  ring owners that are dead or breaker-cooled. The pick reuses the
  fleet's policy verbatim (`fleet.select_member`): healthy members
  with free window credit win by least outstanding work, every member
  cooled degrades to limp mode, LRU tiebreak.
- **Failover redispatch.** A worker that dies mid-request (transport
  error + exited process, or connection refused) gets ONE redispatch
  to the next owner in ring order before the client sees an error —
  and the dead worker leaves the ring, so its keys migrate to exactly
  the workers that absorb its traffic (minimal movement).
- **Backpressure, composed with tenant admission.** Per-worker
  in-flight windows bound what the gateway will queue on any one
  worker; a full owner is a 503 with Retry-After (spilling an
  affinity key would duplicate its cache entry — shedding is the
  honest move). Tenant headers (X-Tenant, X-Deadline-Ms,
  X-Accuracy-Class) pass through untouched: the PR 18 scheduler's
  429/504 verdicts come back from the worker as-is, so gateway
  backpressure stacks UNDER tenant admission, never replaces it.

Cluster epoch — no mixed-version window, ever:

The PR 10 cache generalized "promote" to an invalidation epoch inside
one process; the gateway generalizes it across processes. A fleet-wide
promote (admin POST /models/promote, or SIGHUP) runs TWO-PHASE:
prepare (load + pre-warm the version on every worker — slow, traffic
keeps flowing) then flip (pause admission, drain the gateway's
in-flight window to zero, promote every worker, fan the new epoch out,
bump the gateway's own epoch, resume). Workers stamp every /predict
response with X-Cluster-Epoch; the gateway compares each reply's epoch
against the epoch it admitted the request under and 503s a mismatch
(`mixed_epoch_rejected` — asserted zero by the bench: with the
pause-drain barrier the mismatch path is unreachable unless a worker
is bypassed or wedged). A rolling version change therefore never
serves two versions to one client: either the old fleet answered
before the barrier or the new fleet after it.

The cluster epoch is mutated ONLY inside `promote_fanout` (gateway
side) and `apply_cluster_epoch` (worker side) — lint DML018 enforces
the containment the way DML017 pins the tenancy state to its lock.

Observability: gateway spans (`gateway.route` / `gateway.dispatch` /
`gateway.failover`) join the trace vocabulary, and cross-process
correlation rides two headers — the gateway sends X-Gateway-Trace-Id
to the worker and tags its dispatch span with the worker's X-Trace-Id,
so one request's gateway trace and worker trace name each other from
both sides. /metrics serves the `dmnist_gateway_*` Prometheus series
(serve/metrics.py `gateway_prometheus_exposition`).

stdlib-only like serve.py: http.server on the front, pooled
http.client connections to the workers.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import itertools
import json
import logging
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Any, Optional, Sequence

from distributedmnist_tpu.analysis.locks import (make_condition, make_lock,
                                                 make_thread)
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.resilience import (CircuitBreaker,
                                                   HealthTracker)

log = logging.getLogger("distributedmnist_tpu")

IMAGE_BYTES = 28 * 28

# Tenant/SLO/trace headers forwarded to the worker untouched (ISSUE 18
# composition: the worker's scheduler sees exactly what the client
# sent) and the worker response headers surfaced back to the client.
_FORWARD_HEADERS = ("X-Deadline-Ms", "X-Accuracy-Class", "X-Tenant",
                    "X-Server-Timing")
_SURFACE_HEADERS = ("X-Trace-Id", "X-Cluster-Epoch", "Retry-After",
                    "Server-Timing")


class GatewayShed(RuntimeError):
    """A request the gateway refuses to dispatch (backpressure, empty
    fleet, promote-pause timeout): 503 semantics, counted by reason."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason
        self.status = 503


def ring_key(version: Optional[str], infer_dtype: Optional[str],
             rows: int, digest: bytes) -> bytes:
    """The ring's hash input for one request — the same identity tuple
    the PR 10 cache keys entries by (cache.content_key), serialized to
    bytes. Keeping the identities equal is the whole sharding argument:
    a key's cache entry lives on a worker if and only if the ring sent
    every repeat of that key there."""
    return (f"{version}|{infer_dtype}|{rows}|".encode()
            + digest)


class HashRing:
    """Consistent-hash ring with virtual nodes (sha256 points).

    Placement is deterministic (pure function of the member set), key
    movement on join/leave is minimal (a joining member takes keys only
    FROM successors of its own vnodes; a leaving member's keys move
    only TO its ring successors — nothing else re-maps), and
    `owners(key)` yields the failover order: the owner first, then each
    next distinct member clockwise. Not thread-safe by itself — the
    Gateway mutates it only under its routing condition."""

    def __init__(self, members: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._members: set = set()
        self._points: list = []      # sorted [(point, member), ...]
        for m in members:
            self.add(m)

    @staticmethod
    def _hash(data: bytes) -> int:
        return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")

    def _vnode_points(self, member: str) -> list:
        return [self._hash(f"{member}#{i}".encode())
                for i in range(self.vnodes)]

    def add(self, member: str) -> None:
        if member in self._members:
            raise ValueError(f"member {member!r} already on the ring")
        self._members.add(member)
        for pt in self._vnode_points(member):
            bisect.insort(self._points, (pt, member))

    def remove(self, member: str) -> None:
        if member not in self._members:
            raise KeyError(f"member {member!r} not on the ring")
        self._members.discard(member)
        self._points = [p for p in self._points if p[1] != member]

    def members(self) -> list:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def owners(self, key: bytes, n: Optional[int] = None) -> list:
        """Distinct members in ring order from the key's successor
        point: owners(key)[0] is the placement, [1] the first failover
        target, and so on. Empty ring -> empty list."""
        if not self._points:
            return []
        want = len(self._members) if n is None else min(
            n, len(self._members))
        h = self._hash(key)
        idx = bisect.bisect_right(self._points, (h, "￿"))
        out: list = []
        for i in range(len(self._points)):
            member = self._points[(idx + i) % len(self._points)][1]
            if member not in out:
                out.append(member)
                if len(out) >= want:
                    break
        return out

    def owner(self, key: bytes) -> Optional[str]:
        got = self.owners(key, n=1)
        return got[0] if got else None


class WorkerTransport:
    """Pooled HTTP/1.1 client to one worker: keep-alive connections
    reused across requests (the closed-loop bench would otherwise pay
    a TCP handshake per image), broken connections dropped, never
    reused. The pool lock guards only list ops — I/O runs outside it."""

    def __init__(self, host: str, port: int, timeout_s: float = 75.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._lock = make_lock(f"gateway.pool.{port}")
        self._free: deque = deque()

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout_s: Optional[float] = None) -> tuple:
        """One round trip: (status, response headers dict, body bytes).
        Raises OSError/http.client.HTTPException on transport failure —
        the caller's failover cue."""
        import http.client

        with self._lock:
            conn = self._free.popleft() if self._free else None
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port,
                timeout=(self.timeout_s if timeout_s is None
                         else timeout_s))
        elif timeout_s is not None and conn.sock is not None:
            conn.sock.settimeout(timeout_s)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            out_headers = dict(resp.getheaders())
            status = resp.status
        except Exception:
            conn.close()          # a broken connection is never pooled
            raise
        if timeout_s is not None and conn.sock is not None:
            conn.sock.settimeout(self.timeout_s)
        with self._lock:
            self._free.append(conn)
        return status, out_headers, data

    def close(self) -> None:
        with self._lock:
            conns = list(self._free)
            self._free.clear()
        for c in conns:
            c.close()


@dataclasses.dataclass
class _Worker:
    """One fleet member: its transport plus the live routing
    accounting, all mutable fields guarded by the Gateway's routing
    condition. Field names mirror fleet._Replica so the shared pick
    policy (fleet.select_member) reads both. `outstanding_s` is in ROW
    units here — the gateway holds no warmup cost tables, so
    least-outstanding-rows is its cost-aware analogue."""

    rid: str
    port: int
    transport: Any
    proc: Any = None                 # subprocess handle (None in tests)
    state: str = "active"            # "active" | "dead"
    inflight: int = 0
    outstanding_s: float = 0.0
    last_pick: int = 0
    dispatched: int = 0
    rescued: int = 0
    failures: int = 0


class Gateway:
    """The routing core: admission, ring/least-loaded dispatch,
    failover, the cluster epoch, and the two-phase promote. HTTP
    serving and process spawning live in run_gateway() — this class
    takes any transport-shaped workers, so the unit tests drive it
    with in-memory fakes (no sockets)."""

    #: bounded wait for a promote flip before a request sheds (the
    #: flip itself is sub-second: promote + epoch POSTs on warm
    #: workers — prepare ran before the pause)
    pause_wait_s = 10.0
    #: bounded wait for the in-flight window to drain at the flip
    drain_timeout_s = 30.0

    def __init__(self, workers: Sequence[_Worker],
                 worker_inflight: int = 8, vnodes: int = 64,
                 affinity: bool = True,
                 breaker: Optional[CircuitBreaker] = None,
                 health: Optional[HealthTracker] = None):
        if not workers:
            raise ValueError("a gateway needs at least one worker")
        if worker_inflight < 1:
            raise ValueError(
                f"worker_inflight must be >= 1, got {worker_inflight}")
        self._cond = make_condition("gateway.route")
        # Serializes admin fan-outs (load/promote/SIGHUP): held across
        # multi-second worker warmups BY DESIGN — admin threads only,
        # never the dispatch path.
        self._admin = make_lock("gateway.admin", blocking_ok=True)
        self._workers: dict = {w.rid: w for w in workers}
        if len(self._workers) != len(workers):
            raise ValueError("duplicate worker rid")
        self.worker_inflight = worker_inflight
        self.affinity = affinity
        self.ring = HashRing([w.rid for w in workers], vnodes=vnodes)
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            window_s=5.0, min_requests=8, failure_ratio=0.5,
            cooldown_s=5.0)
        self.health = health if health is not None else HealthTracker()
        self._cluster_epoch = 0
        self._live_version: Optional[str] = None
        self._live_dtype: Optional[str] = None
        self._paused = False
        self._pick_seq = 0
        self._rid_seq = itertools.count(1)
        # counters (all under self._cond)
        self._requests = 0
        self._routed_affinity = 0
        self._routed_balanced = 0
        self._failovers = 0
        self._failover_rescued = 0
        self._backpressure_503 = 0
        self._paused_503 = 0
        self._mixed_epoch_rejected = 0
        self._worker_deaths = 0
        self._promotes = 0

    # -- boot --------------------------------------------------------------

    def start(self) -> None:
        """Fan the initial epoch out so every worker stamps responses
        from request one (a stampless reply would be indistinguishable
        from a pre-gateway worker), then learn the live route identity
        for ring keying (best-effort: workers may still be warming —
        refresh_route() is retried per request until it lands)."""
        for w in self._active():
            try:
                w.transport.request(
                    "POST", "/cluster/epoch",
                    json.dumps({"epoch": self._cluster_epoch}).encode(),
                    {"Content-Type": "application/json"})
            except Exception as e:
                log.warning("gateway: epoch seed to %s failed: %s",
                            w.rid, e)
        self.refresh_route()

    def refresh_route(self) -> None:
        """Re-learn (live_version, live_infer_dtype) from the first
        worker that answers /healthz — the ring-key identity. Workers
        promote in lockstep (the fan-out is the only admin path), so
        any one worker's answer speaks for the fleet."""
        for w in self._active():
            try:
                _, _, body = w.transport.request("GET", "/healthz",
                                                 timeout_s=5.0)
                payload = json.loads(body)
            except Exception:
                continue
            if payload.get("live_version") is not None:
                with self._cond:
                    self._live_version = payload["live_version"]
                    self._live_dtype = payload.get("live_infer_dtype")
                return

    def _active(self) -> list:
        with self._cond:
            return [w for w in self._workers.values()
                    if w.state == "active"]

    # -- admission + dispatch ----------------------------------------------

    def _admit(self, key: Optional[bytes], rows: int) -> tuple:
        """Pick + reserve a worker under the routing condition; returns
        (admission epoch, worker, failover order). Raises GatewayShed
        on backpressure / pause timeout / empty fleet. The slot is
        reserved HERE, under the lock, so concurrent admits can never
        oversubscribe a window — exactly the fleet's reservation
        discipline."""
        from distributedmnist_tpu.serve.fleet import select_member

        with self._cond:
            t_end = time.monotonic() + self.pause_wait_s
            while self._paused:
                if time.monotonic() >= t_end:
                    self._paused_503 += 1
                    raise GatewayShed(
                        "promote_pause",
                        "fleet promote in progress; retry")
                self._cond.wait(0.05)
            self._requests += 1
            active = [w for w in self._workers.values()
                      if w.state == "active"]
            if not active:
                raise GatewayShed("no_workers",
                                  "every worker is dead")
            pick = None
            failover: list = []
            if key is not None:
                order = [rid for rid in self.ring.owners(key)
                         if rid in self._workers
                         and self._workers[rid].state == "active"]
                cands = [self._workers[rid] for rid in order]
                # first non-cooled owner in ring order; all cooled
                # degrades to the raw ring order (limp mode — the
                # fleet's rule: a grim health window is never a
                # self-inflicted outage)
                pick = next((w for w in cands
                             if not self.breaker.in_cooldown(w.rid)),
                            cands[0] if cands else None)
                if pick is not None:
                    if pick.inflight >= self.worker_inflight:
                        # The owner is saturated. Spilling this key to
                        # a sibling would compute AND cache it there —
                        # a duplicate entry by construction — so the
                        # gateway sheds instead: backpressure IS the
                        # sharding contract under overload.
                        self._backpressure_503 += 1
                        raise GatewayShed(
                            "backpressure",
                            f"worker {pick.rid} (ring owner) is at its "
                            f"in-flight window ({self.worker_inflight})")
                    failover = [rid for rid in order if rid != pick.rid]
                    self._routed_affinity += 1
            if pick is None:
                pick = select_member(active, self.breaker.in_cooldown,
                                     self.worker_inflight)
                if pick is None:
                    self._backpressure_503 += 1
                    raise GatewayShed(
                        "backpressure",
                        "every worker is at its in-flight window")
                failover = [w.rid for w in active if w.rid != pick.rid]
                self._routed_balanced += 1
            self._pick_seq += 1
            pick.last_pick = self._pick_seq
            pick.inflight += 1
            pick.outstanding_s += rows
            return self._cluster_epoch, pick, failover

    def _release(self, w: _Worker, rows: int) -> None:
        with self._cond:
            w.inflight -= 1
            w.outstanding_s = max(w.outstanding_s - rows, 0.0)
            self._cond.notify_all()

    def _record(self, w: _Worker, ok: bool, rows: int,
                latency_s: Optional[float] = None) -> None:
        self.health.record(w.rid, ok, n=rows, latency_s=latency_s)
        if not ok:
            with self._cond:
                w.failures += 1
        if self.breaker.record(w.rid, ok, n=rows):
            log.warning("gateway: worker %s TRIPPED its breaker — "
                        "routed around for %.1fs", w.rid,
                        self.breaker.cooldown_s)

    def _mark_dead(self, w: _Worker) -> None:
        """A worker whose process exited (or refuses connections)
        leaves the pick set AND the ring — its keys migrate to their
        next owners, which is exactly where its in-flight requests
        fail over to."""
        with self._cond:
            if w.state == "dead":
                return
            w.state = "dead"
            self._worker_deaths += 1
            if w.rid in self.ring:
                self.ring.remove(w.rid)
            self._cond.notify_all()
        log.warning("gateway: worker %s (port %d) is DEAD — removed "
                    "from the ring, keys migrate to ring successors",
                    w.rid, w.port)

    def _is_death(self, w: _Worker, exc: BaseException) -> bool:
        if w.proc is not None and w.proc.poll() is not None:
            return True
        return isinstance(exc, ConnectionRefusedError)

    # -- autoscale actuation (ISSUE 20) ------------------------------------

    def add_worker(self, worker: _Worker) -> None:
        """Autoscaler grow actuation — ONLY the autoscaler's actuator
        path calls this (lint DML019); boot-time membership goes
        through the constructor. Joins an already-spawned worker to
        the pick set and the ring, under the admin lock so a join can
        never interleave with a promote fan-out (a worker added
        mid-flip would miss the flip and serve the old version behind
        a new epoch). The worker is seeded with the current cluster
        epoch BEFORE it enters the ring: its very first reply must
        stamp correctly or the gateway itself would reject it as
        mixed-epoch."""
        with self._admin:
            with self._cond:
                if worker.rid in self._workers:
                    raise ValueError(
                        f"worker {worker.rid!r} already joined")
                epoch = self._cluster_epoch
            try:
                worker.transport.request(
                    "POST", "/cluster/epoch",
                    json.dumps({"epoch": epoch}).encode(),
                    {"Content-Type": "application/json"})
            except Exception as e:
                log.warning("gateway: epoch seed to joining worker "
                            "%s failed: %s", worker.rid, e)
            with self._cond:
                self._workers[worker.rid] = worker
                self.ring.add(worker.rid)
                self._cond.notify_all()
        log.info("gateway: worker %s (port %d) JOINED the ring "
                 "(autoscale)", worker.rid, worker.port)

    def drain_worker(self, rid: str,
                     timeout_s: float = 30.0) -> _Worker:
        """Autoscaler shrink actuation — ONLY the autoscaler's
        actuator path calls this (lint DML019). Two-step exit: the
        worker leaves the ring and the pick set FIRST (no new
        admissions; its keys migrate to ring successors exactly as on
        death, but without failing anything), then its in-flight
        requests drain up to `timeout_s` before it is handed back to
        the caller to terminate. Never drains the last active worker —
        the floor is the actuator's contract, but a fleet of zero
        routes nothing and must be impossible at this layer too."""
        with self._admin:
            with self._cond:
                w = self._workers.get(rid)
                if w is None or w.state != "active":
                    raise ValueError(
                        f"no active worker {rid!r} to drain")
                actives = sum(1 for x in self._workers.values()
                              if x.state == "active")
                if actives <= 1:
                    raise ValueError(
                        "cannot drain the last active worker")
                if rid in self.ring:
                    self.ring.remove(rid)
                w.state = "draining"
                self._cond.notify_all()
            deadline = time.monotonic() + timeout_s
            with self._cond:
                while w.inflight and time.monotonic() < deadline:
                    self._cond.wait(0.1)
                del self._workers[rid]
                self._cond.notify_all()
        log.info("gateway: worker %s (port %d) DRAINED and left the "
                 "ring (autoscale)", w.rid, w.port)
        return w

    def handle_predict(self, body: bytes, headers: dict) -> tuple:
        """Route one /predict: returns (status, response headers,
        response body bytes). Transport failure on the picked worker
        gets one failover redispatch to the next ring owner; a reply
        stamped with a different epoch than the request was admitted
        under is rejected (503) — mixed-epoch replies must never reach
        a client."""
        t0 = time.monotonic()
        if not body or len(body) % IMAGE_BYTES:
            return (400, {}, json.dumps(
                {"error": "body must be n*784 raw uint8 pixel "
                          "bytes"}).encode())
        rows = len(body) // IMAGE_BYTES
        tracer = trace.active()
        tid = None
        rid = 0
        if tracer is not None:
            rid = next(self._rid_seq)
            tid = tracer.start_request(rid, rows=rows, t0=t0)
        fwd = {k: headers[k] for k in _FORWARD_HEADERS if k in headers}
        fwd["Content-Type"] = "application/octet-stream"
        if tid is not None:
            fwd["X-Gateway-Trace-Id"] = tid
        error: Optional[BaseException] = None
        try:
            status, rhdrs, rbody, worker = self._route_once(
                body, fwd, rows, rid)
        except GatewayShed as e:
            error = e
            out = {"Retry-After": "1"}
            if tid is not None:
                out["X-Gateway-Trace-Id"] = tid
            return (503, out, json.dumps(
                {"error": str(e), "reason": e.reason}).encode())
        except Exception as e:
            error = e
            out = {"X-Gateway-Trace-Id": tid} if tid is not None else {}
            return (502, out, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode())
        finally:
            if tracer is not None:
                tracer.finish_request(rid, error=error)
        out = {k: rhdrs[k] for k in _SURFACE_HEADERS if k in rhdrs}
        out["X-Gateway-Worker"] = worker.rid
        if tid is not None:
            out["X-Gateway-Trace-Id"] = tid
        return status, out, rbody

    def _route_once(self, body: bytes, fwd: dict, rows: int,
                    rid: int) -> tuple:
        """Admit, dispatch, failover-once, epoch-check. Returns
        (status, worker headers, worker body, worker)."""
        with self._cond:
            version, dtype = self._live_version, self._live_dtype
        if version is None:
            # the route identity may simply not be learned yet
            # (workers were warming at start()) — retry cheaply
            self.refresh_route()
            with self._cond:
                version, dtype = self._live_version, self._live_dtype
        key = None
        if self.affinity and version is not None:
            key = ring_key(version, dtype, rows,
                           hashlib.sha256(body).digest())
        sp = trace.begin_span("gateway.route", rids=[rid], rows=rows)
        try:
            epoch, worker, failover = self._admit(key, rows)
        finally:
            trace.end_span(sp)
        status = rhdrs = rbody = None
        sp = trace.begin_span("gateway.dispatch", rids=[rid],
                              worker=worker.rid)
        try:
            t_d0 = time.monotonic()
            try:
                status, rhdrs, rbody = worker.transport.request(
                    "POST", "/predict", body, fwd)
            except Exception as e:
                self._release(worker, rows)
                self._record(worker, False, rows)
                if self._is_death(worker, e):
                    self._mark_dead(worker)
                trace.end_span(sp, error=type(e).__name__)
                sp = None
                status, rhdrs, rbody, worker = self._failover(
                    body, fwd, rows, rid, worker, failover, e)
            else:
                self._release(worker, rows)
                self._record(worker, status < 500 or status in (503, 504),
                             rows, latency_s=time.monotonic() - t_d0)
                with self._cond:
                    worker.dispatched += 1
                if sp is not None and "X-Trace-Id" in rhdrs:
                    # cross-process join: the gateway span names the
                    # worker's trace, the worker's trace carries the
                    # gateway id via X-Gateway-Trace-Id
                    sp.tags["worker_trace_id"] = rhdrs["X-Trace-Id"]
        finally:
            trace.end_span(sp)
        reply_epoch = rhdrs.get("X-Cluster-Epoch")
        if status == 200 and reply_epoch is not None \
                and int(reply_epoch) != epoch:
            with self._cond:
                self._mixed_epoch_rejected += 1
            log.warning(
                "gateway: REJECTED mixed-epoch reply from %s (admitted "
                "epoch %d, reply epoch %s)", worker.rid, epoch,
                reply_epoch)
            raise GatewayShed(
                "mixed_epoch",
                f"reply computed under cluster epoch {reply_epoch}, "
                f"request admitted under {epoch}; retry")
        return status, rhdrs, rbody, worker

    def _failover(self, body: bytes, fwd: dict, rows: int, rid: int,
                  failed: _Worker, failover: list,
                  cause: BaseException) -> tuple:
        """ONE redispatch to the next ring owner (or the next
        least-loaded active worker on the balanced path). A rescue may
        transiently exceed the window (overflow), like the fleet's —
        refusing the rescue for credit would turn one death into two
        failures."""
        with self._cond:
            self._failovers += 1
            rescue = next(
                (self._workers[r] for r in failover
                 if r in self._workers
                 and self._workers[r].state == "active"), None)
            if rescue is not None:
                self._pick_seq += 1
                rescue.last_pick = self._pick_seq
                rescue.inflight += 1
                rescue.outstanding_s += rows
        if rescue is None:
            raise GatewayShed(
                "no_workers",
                f"worker {failed.rid} died mid-request "
                f"({type(cause).__name__}) and no sibling remains")
        sp = trace.begin_span("gateway.failover", rids=[rid],
                              failed=failed.rid, rescue=rescue.rid)
        try:
            t0 = time.monotonic()
            try:
                status, rhdrs, rbody = rescue.transport.request(
                    "POST", "/predict", body, fwd)
            except Exception as e:
                self._release(rescue, rows)
                self._record(rescue, False, rows)
                if self._is_death(rescue, e):
                    self._mark_dead(rescue)
                raise RuntimeError(
                    f"worker {failed.rid} died mid-request "
                    f"({type(cause).__name__}); failover to "
                    f"{rescue.rid} also failed "
                    f"({type(e).__name__}: {e})") from e
            self._release(rescue, rows)
            self._record(rescue, status < 500 or status in (503, 504),
                         rows, latency_s=time.monotonic() - t0)
            with self._cond:
                rescue.dispatched += 1
                rescue.rescued += 1
                self._failover_rescued += 1
        finally:
            trace.end_span(sp)
        return status, rhdrs, rbody, rescue

    # -- admin: fleet-wide model lifecycle ---------------------------------

    def load_fanout(self, body: dict) -> tuple:
        """Phase-1-only admin surface (POST /models/load): load +
        pre-warm on EVERY active worker, no routing change, no epoch
        change. Aborts on the first failure — a fleet where only some
        workers hold the candidate would turn the later flip into a
        partial outage. Returns (status, payload)."""
        with self._admin:
            return self._load_fanout_locked(body)

    def _load_fanout_locked(self, body: dict) -> tuple:
        live = self._active()
        if not live:
            return 503, {"error": "every worker is dead"}
        results = {}
        for w in live:
            try:
                st, _, rbody = w.transport.request(
                    "POST", "/models/load",
                    json.dumps(body).encode(),
                    {"Content-Type": "application/json"},
                    timeout_s=600.0)
            except Exception as e:
                return 502, {
                    "error": f"prepare failed on {w.rid}: "
                             f"{type(e).__name__}: {e}",
                    "prepared": results}
            payload = _json_or_raw(rbody)
            if st != 200:
                return st, {
                    "error": f"prepare failed on {w.rid}",
                    "worker_response": payload,
                    "prepared": results}
            results[w.rid] = payload
        versions = {r.get("version") for r in results.values()
                    if isinstance(r, dict)}
        return 200, {"prepared": results,
                     "version": (versions.pop()
                                 if len(versions) == 1 else None),
                     "workers": sorted(results)}

    def promote_fanout(self, version: Optional[str] = None,
                       load: Optional[dict] = None,
                       infer_dtype: Optional[str] = None) -> tuple:
        """The fleet-wide promote — and the ONLY place the gateway's
        cluster epoch mutates (lint DML018). Two-phase:

        phase 1 (prepare): when `load` is given, load + pre-warm it on
        every worker while traffic keeps flowing (a prior load_fanout
        also satisfies this phase). No routing change yet.

        phase 2 (flip): pause admission, drain the gateway's in-flight
        window to zero, promote every worker, fan the bumped epoch
        out, bump the gateway's own epoch, resume. Requests admitted
        before the pause completed against the OLD fleet; requests
        after resume dispatch against the NEW one — the mixed-epoch
        window is empty by construction, and the per-reply epoch check
        in handle_predict stays as the tripwire.

        A mid-flip worker failure rolls the already-flipped workers
        back to the old version before resuming (a worker that also
        fails the rollback is marked dead — it can only serve stamped
        replies the epoch check rejects)."""
        with self._admin:
            live = self._active()
            if not live:
                return 503, {"error": "every worker is dead"}
            if load is not None:
                st, payload = self._load_fanout_locked(load)
                if st != 200:
                    return st, payload
                if version is None:
                    version = payload.get("version")
            if not version:
                return 400, {"error": "no 'version' (and no unambiguous "
                                      "prepared version to infer)"}
            with self._cond:
                old_version = self._live_version
                new_epoch = self._cluster_epoch + 1
                self._paused = True
                self._cond.notify_all()
            try:
                self._drain_inflight()
                flipped: list = []
                promote_body = {"version": version, "mode": "live"}
                if infer_dtype is not None:
                    promote_body["infer_dtype"] = infer_dtype
                for w in live:
                    st, _, rbody = _admin_post(w, "/models/promote",
                                               promote_body)
                    if st != 200:
                        self._rollback(flipped, old_version)
                        return 409, {
                            "error": f"promote failed on {w.rid} "
                                     "(fleet rolled back)",
                            "worker_response": _json_or_raw(rbody)}
                    flipped.append(w)
                for w in live:
                    st, _, rbody = _admin_post(
                        w, "/cluster/epoch", {"epoch": new_epoch})
                    if st != 200:
                        # a worker serving the new version under the
                        # old epoch would stamp replies the epoch
                        # check rejects — remove it rather than serve
                        # rejectable answers from it
                        self._mark_dead(w)
                with self._cond:
                    self._cluster_epoch = new_epoch
                    self._live_version = version
                    self._promotes += 1
            finally:
                with self._cond:
                    self._paused = False
                    self._cond.notify_all()
            self.refresh_route()     # live dtype may have changed
            log.info("gateway: fleet promoted to %s, cluster epoch %d "
                     "(%d workers)", version, new_epoch, len(live))
            return 200, {"promoted": version,
                         "cluster_epoch": new_epoch,
                         "workers": [w.rid for w in live]}

    def _drain_inflight(self) -> None:
        with self._cond:
            t_end = time.monotonic() + self.drain_timeout_s
            while any(w.inflight for w in self._workers.values()):
                if time.monotonic() >= t_end:
                    raise RuntimeError(
                        "gateway in-flight window failed to drain for "
                        "the promote flip")
                self._cond.wait(0.05)

    def _rollback(self, flipped: list, old_version: Optional[str]) -> None:
        if old_version is None:
            return
        for w in flipped:
            try:
                st, _, _ = _admin_post(w, "/models/promote",
                                       {"version": old_version})
                if st != 200:
                    self._mark_dead(w)
            except Exception:
                self._mark_dead(w)

    # -- observability -----------------------------------------------------

    def healthz(self) -> tuple:
        """Fleet health: 200 while at least one worker answers ok.
        Worker rows carry the per-worker port + live version + epoch —
        the bench reads worker ports from here to poll per-worker cache
        counters directly."""
        workers = []
        any_ok = False
        for w in list(self._workers.values()):
            row = {"worker": w.rid, "port": w.port, "state": w.state}
            if w.state == "active":
                try:
                    st, _, body = w.transport.request(
                        "GET", "/healthz", timeout_s=5.0)
                    payload = json.loads(body)
                    row.update(
                        ok=bool(payload.get("ok")),
                        live_version=payload.get("live_version"),
                        live_infer_dtype=payload.get("live_infer_dtype"),
                        cluster_epoch=payload.get("cluster_epoch"),
                        state_detail=payload.get("state"))
                    any_ok = any_ok or bool(payload.get("ok"))
                except Exception as e:
                    row.update(ok=False,
                               error=f"{type(e).__name__}: {e}")
            else:
                row["ok"] = False
            workers.append(row)
        with self._cond:
            payload = {
                "ok": any_ok,
                "cluster_epoch": self._cluster_epoch,
                "live_version": self._live_version,
                "paused": self._paused,
                "workers": workers,
            }
        return (200 if any_ok else 503), payload

    def snapshot(self) -> dict:
        """The dmnist_gateway_* source of truth: JSON /metrics block,
        Prometheus exposition input, and the gateway_summary exit
        record."""
        with self._cond:
            per_worker = [
                {"worker": w.rid, "port": w.port, "state": w.state,
                 "inflight": w.inflight,
                 "outstanding_rows": w.outstanding_s,
                 "dispatched": w.dispatched, "rescued": w.rescued,
                 "failures": w.failures}
                for w in self._workers.values()]
            return {
                "workers": len(self._workers),
                "workers_active": sum(
                    1 for w in self._workers.values()
                    if w.state == "active"),
                "cluster_epoch": self._cluster_epoch,
                "live_version": self._live_version,
                "live_infer_dtype": self._live_dtype,
                "paused": self._paused,
                "worker_inflight": self.worker_inflight,
                "affinity": self.affinity,
                "requests": self._requests,
                "routed_affinity": self._routed_affinity,
                "routed_balanced": self._routed_balanced,
                "failovers": self._failovers,
                "failover_rescued": self._failover_rescued,
                "backpressure_503": self._backpressure_503,
                "paused_503": self._paused_503,
                "mixed_epoch_rejected": self._mixed_epoch_rejected,
                "worker_deaths": self._worker_deaths,
                "promotes": self._promotes,
                "per_worker": per_worker,
                "health": self.health.snapshot(),
                "breaker": self.breaker.snapshot(),
            }


def _admin_post(w: _Worker, path: str, body: dict) -> tuple:
    return w.transport.request(
        "POST", path, json.dumps(body).encode(),
        {"Content-Type": "application/json"}, timeout_s=600.0)


def _json_or_raw(body: bytes):
    try:
        return json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return body.decode("utf-8", "replace")


# -- process spawning + the HTTP front door --------------------------------


def worker_argv(gateway_argv: Sequence[str]) -> list:
    """The worker command line: the gateway's own argv with the
    gateway-layer flags stripped and an ephemeral port appended —
    every serving flag (--model, --serve-cache, --serve-max-batch,
    --checkpoint-dir, ...) forwards verbatim, so a worker is exactly
    the serve.py the operator configured, times N."""
    takes_value = {"--gateway", "--gateway-worker-inflight",
                   "--gateway-vnodes", "--port"}
    out: list = []
    skip = False
    for a in gateway_argv:
        if skip:
            skip = False
            continue
        if a in takes_value:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in takes_value):
            continue
        out.append(a)
    return out + ["--port", "0"]


def spawn_worker(rid: str, argv: Sequence[str],
                 ready_timeout_s: float = 180.0) -> _Worker:
    """Start one serve.py worker subprocess and wait for its
    serve_ready line (printed after bind, BEFORE warmup — warm state
    is polled via /healthz). stderr passes through to the gateway's
    stderr (worker logs stay visible); stdout is drained on a thread
    so heartbeat lines can never fill the pipe and wedge the worker."""
    serve_py = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "serve.py")
    proc = subprocess.Popen(
        [sys.executable, serve_py] + list(argv),
        stdout=subprocess.PIPE, text=True)
    port = None
    t_end = time.monotonic() + ready_timeout_s
    while time.monotonic() < t_end:
        line = proc.stdout.readline()
        if not line:
            break
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == "serve_ready":
            port = int(rec["port"])
            break
    if port is None:
        proc.terminate()
        raise RuntimeError(
            f"worker {rid} printed no serve_ready line within "
            f"{ready_timeout_s:.0f}s (exit code {proc.poll()})")

    def _drain():
        for line in proc.stdout:
            line = line.rstrip()
            if line:
                print(json.dumps({"metric": "worker_line",
                                  "worker": rid, "line": line}),
                      flush=True)

    make_thread(target=_drain, name=f"gateway-drain-{rid}",
                daemon=True).start()
    return _Worker(rid=rid, port=port, proc=proc,
                   transport=WorkerTransport("127.0.0.1", port))


def run_gateway(args, argv: Sequence[str]) -> int:
    """serve.py --gateway N main loop: spawn the workers, bind the
    front door, announce gateway_ready, route until SIGTERM. SIGHUP
    fans the checkpoint reload out fleet-wide through the two-phase
    promote (the single-process serve.py semantic, generalized)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from distributedmnist_tpu.serve.metrics import \
        gateway_prometheus_exposition

    n = args.gateway_workers
    wargv = worker_argv(argv)
    log.info("gateway: spawning %d workers: serve.py %s", n,
             " ".join(wargv))
    workers: list = []
    try:
        for i in range(n):
            workers.append(spawn_worker(f"w{i}", wargv))
    except Exception:
        for w in workers:
            if w.proc is not None:
                w.proc.terminate()
        raise
    gw = Gateway(workers,
                 worker_inflight=args.gateway_worker_inflight,
                 vnodes=args.gateway_vnodes,
                 affinity=bool(args.serve_cache))
    if getattr(args, "serve_trace", False):
        # The gateway runs its OWN tracer (workers each run theirs —
        # --serve-trace forwards to them too); the X-Gateway-Trace-Id
        # / X-Trace-Id header exchange in handle_predict joins the two
        # processes' traces from both sides.
        trace.install(trace.Tracer(
            capacity=args.serve_trace_capacity,
            sample=args.serve_trace_sample,
            slo_ms=args.serve_slo_ms, seed=args.seed))

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            pass

        def _send(self, code: int, payload: dict,
                  extra: Optional[dict] = None) -> None:
            self._send_bytes(code, json.dumps(payload).encode(),
                             "application/json", extra)

        def _send_bytes(self, code: int, body: bytes,
                        content_type: str,
                        extra: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        def _json_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            body = json.loads(raw) if raw.strip() else {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body

        def do_GET(self):
            if self.path == "/healthz":
                code, payload = gw.healthz()
                self._send(code, payload)
            elif self.path == "/trace" or self.path.startswith("/trace?"):
                tracer = trace.active()
                if tracer is None:
                    self._send(409, {
                        "error": "tracing is not enabled; restart with "
                                 "--serve-trace"})
                else:
                    self._send(200, tracer.export_chrome())
            elif (self.path == "/metrics"
                  or self.path.startswith("/metrics?")):
                snap = gw.snapshot()
                if "format=prometheus" in self.path:
                    self._send_bytes(
                        200, gateway_prometheus_exposition(snap).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(200, snap)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path == "/predict":
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                status, hdrs, rbody = gw.handle_predict(
                    body, dict(self.headers))
                self._send_bytes(status, rbody, "application/json",
                                 hdrs)
            elif self.path == "/models/load":
                self._admin(gw.load_fanout)
            elif self.path == "/models/promote":
                self._admin(lambda b: gw.promote_fanout(
                    version=b.get("version"), load=b.get("load"),
                    infer_dtype=b.get("infer_dtype")))
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def _admin(self, fn):
            try:
                body = self._json_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            try:
                code, payload = fn(body)
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(code, payload)

    srv = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    bound = srv.server_address[1]
    print(json.dumps({"metric": "gateway_ready", "port": bound,
                      "workers": n,
                      "worker_ports": [w.port for w in workers]}),
          flush=True)
    gw.start()

    stop = threading.Event()

    def _beat():
        while not stop.wait(args.metrics_every):
            print(json.dumps({"metric": "gateway_stats",
                              **gw.snapshot()}), flush=True)

    make_thread(target=_beat, name="gateway-heartbeat",
                daemon=True).start()

    def _shutdown(signum, frame):
        make_thread(target=srv.shutdown, name="gateway-shutdown",
                    daemon=True).start()

    def _reload(signum, frame):
        def run():
            code, payload = gw.promote_fanout(load={})
            if code == 200:
                log.info("gateway SIGHUP reload: %s", payload)
            else:
                log.error("gateway SIGHUP reload failed: %s", payload)

        make_thread(target=run, name="gateway-reload",
                    daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGHUP, _reload)
    try:
        srv.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        srv.server_close()
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
            w.transport.close()
    print(json.dumps({"metric": "gateway_summary", "port": bound,
                      **gw.snapshot()}), flush=True)
    return 0
