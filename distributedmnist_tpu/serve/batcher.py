"""Dynamic micro-batcher: coalesce concurrent requests into engine-sized
batches, pipelined through a bounded in-flight window, with bounded-queue
backpressure.

A single MNIST forward is ~microseconds of device time; serving requests
one-at-a-time would be dispatch-bound exactly the way unfused training
steps were (SURVEY.md §7.3). The batcher holds a thread-safe queue of
pending requests and a **dispatch thread** that coalesces whatever is
waiting — up to `max_batch` rows or `max_wait_us` after the oldest
request arrived, whichever comes first — into one engine.dispatch() call
(which pads into a pooled staging buffer and enqueues the jitted
forward without fetching). A **completion thread** fetches results in
dispatch order (engine.fetch()) and fans the sliced rows back out to
per-request futures. Latency-throughput tradeoff in three knobs:
`max_wait_us` bounds the queueing delay a lone request can suffer;
`max_batch` bounds how much traffic one dispatch can absorb;
`max_inflight` bounds how many dispatched-but-unfetched batches may
overlap — batch k's device compute runs while batch k+1 stages on the
host and batch k-1's results fan out, the trainer's bounded async
window (trainer.py max_inflight) ported to serving. At max_inflight=1
the pipeline degenerates to the fully serial chain (the honest baseline
bench.py serve compares against).

Backpressure: admission is bounded by `queue_depth` PENDING rows. Beyond
the watermark submit() raises Rejected (HTTP 503 semantics — serve.py
maps it to exactly that) instead of letting queue delay grow without
bound: under overload a closed feedback to the client keeps the p99 of
ACCEPTED requests near the service time, where an unbounded queue would
melt every request's latency together (the Clipper/Clockwork admission
argument — PAPERS.md).

Scheduling (ISSUE 4, serve/scheduler.py): each drain is run through the
cost-model **batch former** — when the engine's measured per-bucket cost
table says several right-sized dispatches beat one padded covering
bucket (20 rows -> 16+4 instead of 32), the drain is split at request
boundaries and the segments feed the in-flight window back-to-back
(`split=False` restores the single-dispatch behaviour). The coalescing
wait is **adaptive**: an AIMD controller steps the effective wait down
on SLO violations (`slo_ms`) and creeps it back up under headroom, with
the configured `max_wait_us` as a hard cap and an arrival-rate EWMA
bounding the wait at the batch fill time (`adaptive=False` pins the
static wait — serve.py's --no-adaptive).

Resilience (ISSUE 5, serve/resilience.py): requests may carry a
client-supplied **deadline**; an expired request is shed at pop time —
before any device work — failing its future with DeadlineExceeded (504
semantics, the fast path out). A failed multi-request dispatch is
**bisected**: retried as recursively split sub-segments along request
boundaries, so a single poison request is isolated (its cohort-mates
succeed on re-dispatch; only the culprit gets the error). Sub-segments
cover with buckets already on the ladder — isolation never compiles a
new shape. Every fan-out's outcome feeds the per-version circuit
breaker (ResiliencePolicy.record_outcome), whose trip auto-rolls the
live version back. The dispatch site is a named failpoint
(`batch.dispatch`, ctx=request ids) so serve/faults.py can inject
deterministic poison for tests and `bench.py serve --chaos`.

Dedup (ISSUE 10, serve/cache.py): with `dedup=True`, identical rows
inside one coalesced drain (same content hash — the faults.py idiom)
dispatch ONCE: riders attach to their representative request and fan
out from its result slice, so five identical 4-row requests run the
4-row bucket instead of padding a 32. The cross-drain sibling — a
bounded LRU response cache with single-flight collapse of concurrent
identical misses — is the CacheFront layer in serve/cache.py, which
sits in FRONT of this batcher.

Fast lane (ISSUE 14): with `fastlane=True`, a submit that finds the
queue EMPTY and a FREE in-flight window slot skips all of the above —
it dispatches immediately on the caller's thread (the engine's
device-resident staging route when one fits) and blocks on its own
fetch, returning an already-resolved future. The lane decision is one
atomic choice under the queue lock (scheduler.fastlane_eligible + a
slot try-acquire), so contention of any kind routes the submit down
the ordinary coalescing path and every drain/stop/shed invariant is
unchanged; the claimed slot IS the request's in-flight slot, so the
pipeline-depth bound holds across both lanes. Lone requests stop
paying the coalesce wait and two thread hand-offs; loaded traffic
never sees the lane at all (the analysis/harnesses.py
`batcher-fastlane` machine explores the races).

Tracing (ISSUE 9, serve/trace.py): with a tracer installed, every
request's path through this pipeline is recorded as a span tree —
queue wait, the coalesce window, the batch former's plan, dispatch,
the dispatched-but-unfetched window (the ISSUE 2 overlap, visible per
batch), the blocking fetch — plus deadline sheds and bisection splits
as structured child spans. Traces finish BEFORE their futures resolve,
so a response-side lookup (serve.py's Server-Timing) always reads a
complete tree. Uninstalled (the default), every hook is one
module-global None check.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

from distributedmnist_tpu.analysis.locks import (make_condition, make_fifo,
                                                 make_lock, make_semaphore,
                                                 make_thread)
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.faults import failpoint
from distributedmnist_tpu.serve.resilience import DeadlineExceeded
from distributedmnist_tpu.serve.scheduler import (AdaptiveController,
                                                  fastlane_eligible,
                                                  plan_segments)


class Rejected(RuntimeError):
    """Queue past its watermark: shed this request (503 semantics)."""

    status = 503


def resolve_max_inflight(value: Optional[int], platform: str) -> int:
    """The serve_max_inflight auto rule, mirroring the trainer's: an
    explicit value wins; None means 1 on CPU (host staging and "device"
    compute share the same cores, so overlap buys little and depth only
    adds latency) and a small pipeline window on accelerators (serving
    forwards carry no collectives, so the trainer's CPU-deadlock concern
    does not apply — the conservative CPU default is about latency, not
    correctness)."""
    if value is not None:
        if value < 1:
            raise ValueError(f"max_inflight must be >= 1, got {value}")
        return value
    return 1 if platform == "cpu" else 4


@dataclass
class _Request:
    x: "object"                   # (n, 28, 28, 1) uint8 ndarray
    n: int
    t_enqueue: float              # time.monotonic()
    rid: int = 0                  # unique per submit — the identity the
    #   fault injector's request-sticky draws and bisection key on
    deadline: Optional[float] = None   # monotonic; None = no deadline
    future: Future = field(default_factory=Future)
    # Content hash (ISSUE 10): sha256 of the canonical input bytes,
    # computed at submit when dedup is on (or handed down by the
    # CacheFront, which already hashed for its lookup). None = dedup
    # off for this request.
    key: Optional[bytes] = None
    # Intra-batch duplicates riding this request (ISSUE 10): identical
    # rows popped in the same drain dispatch ONCE — this request — and
    # fan the shared slice out to every rider's future at resolution.
    dups: list = field(default_factory=list)
    # Pinned dispatch route (ISSUE 17): an infer_dtype the router must
    # resolve for this request instead of the live default — the
    # cascade's stage requests ("float32" for an escalation / the
    # `exact` class, the cheap dtype for stage 1 / `fast`). None (every
    # pre-cascade caller) keeps the live route. Drains are route-
    # uniform: a batch runs ONE engine's program, so requests pinned to
    # different routes never coalesce together.
    route: Optional[str] = None
    # Attribution tags (ISSUE 18): the tenancy layer stamps
    # {"tenant": ..., "model": ...} here so the request's queue.wait
    # span and its dispatch's batch.dispatch span carry the tenant/
    # model identity end-to-end. None (every direct caller) keeps the
    # pre-tenancy span shape byte-identical.
    tags: Optional[dict] = None


class DynamicBatcher:
    """Dispatch + completion threads over a bounded request queue.

    start()/stop() manage the threads; submit(x) -> Future resolving to
    the request's (n, 10) logits. Coalesced engine.dispatch() calls
    happen on the one dispatch thread and their engine.fetch() calls on
    the one completion thread, in dispatch order — results can never
    reorder across batches. With the fast lane on (ISSUE 14) a
    bypassing submit additionally dispatches AND fetches its own
    single-request batch on the caller's thread; the engine's dispatch/
    fetch are thread-safe for this (the staging pool is locked, the
    resident fast routes are single-flight, and a fetch is per-handle —
    the same property the router's shadow drain thread already relies
    on), and per-request results still cannot reorder: a fast-lane
    future resolves from exactly its own fetch.
    """

    def __init__(self, engine, max_batch: Optional[int] = None,
                 max_wait_us: int = 1000,
                 queue_depth: int = 4096, metrics=None,
                 max_inflight: Optional[int] = None,
                 slo_ms: Optional[float] = None, adaptive: bool = True,
                 split: bool = True, resilience=None,
                 dedup: bool = False, fastlane: bool = False):
        self.engine = engine
        # The single-request bypass lane (ISSUE 14): a submit that
        # finds the queue EMPTY and a FREE in-flight window slot
        # dispatches immediately on the caller's thread — no coalesce
        # timer, no dispatch-thread hand-off, no completion-thread
        # hand-off (the caller blocks on its own fetch). The moment
        # contention appears (pending rows, or every slot held) the
        # lane closes and the submit takes the coalescing path, so
        # batching throughput is untouched under load. The decision is
        # made under the queue lock (scheduler.fastlane_eligible + a
        # slot try-acquire), so the drain/stop/shed invariants — and
        # the PR 11 explored machines — see one atomic choice.
        self.fastlane = fastlane
        # Intra-batch dedup (ISSUE 10): identical rows inside one
        # coalesced drain dispatch once and fan out, shrinking the
        # padded bucket. Off by default — the chaos harness's exact
        # poison-isolation accounting assumes one dispatch row per
        # request, and the cache front's single-flight already
        # collapses cross-drain repeats; serve.py wires it via
        # cfg.serve_dedup.
        self.dedup = dedup
        # ISSUE 5 policy bundle (serve/resilience.py): gates the failed-
        # dispatch bisection path and receives every fan-out outcome for
        # the per-version circuit breaker. None = PR 4 behavior (whole
        # segment fails on a dispatch error, no breaker).
        self.resilience = resilience
        self._rid = itertools.count(1)
        self.max_batch = min(max_batch or engine.max_batch,
                             engine.buckets[-1])
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if slo_ms is not None and slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        self.max_wait_s = max_wait_us / 1e6
        self.queue_depth = queue_depth
        self.metrics = metrics
        # The batch former (split) and the AIMD wait controller
        # (adaptive) — serve/scheduler.py. The controller is inert
        # without an SLO beyond its arrival-rate fill cap, so leaving
        # adaptive=True with slo_ms=None keeps the static behaviour.
        self.split = split
        self.controller = (AdaptiveController(
            self.max_wait_s,
            slo_s=slo_ms / 1e3 if slo_ms is not None else None,
            max_batch=self.max_batch) if adaptive else None)
        # Slot accounting is fleet-aware (ISSUE 6): a ReplicaSet
        # enforces its own bounded PER-REPLICA windows inside dispatch,
        # and advertises their aggregate as max_inflight_total — on
        # auto, the batcher's window opens to exactly that, so the
        # queue can keep every replica's window fed instead of
        # throttling N replicas behind one replica's depth. An explicit
        # max_inflight still wins (bench phases pin it).
        fleet_total = getattr(engine, "max_inflight_total", None)
        if max_inflight is None and fleet_total is not None:
            self.max_inflight = fleet_total
        else:
            self.max_inflight = resolve_max_inflight(
                max_inflight, getattr(engine, "platform", "cpu"))
        self._q: deque[_Request] = deque()
        self._rows = 0                   # pending rows, watermark basis
        self._cond = make_condition("batcher.queue")
        self._stop = False
        # The in-flight window: a slot is held from the moment a batch
        # is popped off the queue until its results have fanned out, so
        # dispatched-but-unresolved batches never exceed max_inflight.
        # Named semaphore: the sanitizer balance-checks slot holds
        # (acquires minus releases must net zero at drain — ISSUE 8).
        self._slots = make_semaphore("batcher.inflight_slots",
                                     self.max_inflight)
        # Autoscale actuation (ISSUE 20): the semaphore's capacity is
        # FIXED at max_inflight (the hard ceiling); the live window is
        # narrowed by PARKING permits — apply_scale acquires them and
        # holds, so the dispatch/fastlane acquire paths see a smaller
        # window with zero new mechanism. Parked permits are returned
        # at stop() (and on widen), so the sanitizer's balance contract
        # (net zero at drain, never negative) holds by construction:
        # permits are never minted or destroyed at runtime.
        self._window_parked = 0          # guarded by self._cond
        self._inflight = 0
        # DISPATCHED-but-unresolved segments only (each holds a window
        # slot, so this never exceeds max_inflight): the depth gauge
        # metrics export. _inflight additionally counts a split drain's
        # popped-but-undispatched segments — the drain predicate — and
        # would read phantom overlap if exported as depth.
        self._dispatched = 0
        self._inflight_lock = make_lock("batcher.inflight_gauge")
        # dispatch -> completion, FIFO; None is the shutdown sentinel.
        # Named factory (ISSUE 11): a bare SimpleQueue in production,
        # an explorable shadow queue under the schedule explorer — the
        # completion hand-off is a yield point, not an opaque block.
        self._handles = make_fifo("batcher.handles")
        self._dispatcher: Optional[threading.Thread] = None
        self._completer: Optional[threading.Thread] = None

    # -- client side -------------------------------------------------------

    def next_rid(self) -> int:
        """A fresh request id from the batcher's sequence — the cache
        front (serve/cache.py) stamps hit/collapsed requests from the
        SAME id space so trace ids never collide across the two entry
        points."""
        return next(self._rid)

    def submit(self, x, deadline_s: Optional[float] = None,
               key: Optional[bytes] = None,
               route: Optional[str] = None,
               tags: Optional[dict] = None) -> Future:
        """Enqueue up to max_batch rows; Future resolves to their logits.
        Raises Rejected past the queue watermark (overload shedding),
        ValueError for requests no single dispatch could ever carry,
        and DeadlineExceeded when `deadline_s` (a time.monotonic()
        deadline, e.g. serve.py's X-Deadline-Ms header) has already
        passed — an expired request must cost zero queue and device
        work. A still-live deadline rides the request into the queue;
        the dispatch thread sheds it at pop time if it expires while
        waiting (the 504-fast path — see _take_batch). `route` pins the
        dispatch to a named infer_dtype (the cascade's stage requests);
        routed requests take the coalescing path only — the fast lane's
        resident program is compiled for the live route. `tags` (the
        tenancy layer's {"tenant", "model"} attribution) ride onto this
        request's queue.wait span and its dispatch's span."""
        x = self.engine._as_images(x)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} rows exceeds max_batch={self.max_batch};"
                " split it client-side")
        now = time.monotonic()
        if deadline_s is not None and now >= deadline_s:
            if self.metrics is not None:
                self.metrics.record_deadline_shed(n)
            raise DeadlineExceeded(
                "deadline already expired at submit "
                f"({(now - deadline_s) * 1e3:.1f} ms ago)")
        if self.dedup and key is None:
            # the faults.py content-hash idiom over the canonical input
            # bytes (~1 us for a 784-byte row; the CacheFront passes
            # its already-computed digest down so the bytes hash once)
            key = hashlib.sha256(x.tobytes()).digest()
        req = _Request(x=x, n=n, t_enqueue=now, rid=next(self._rid),
                       deadline=deadline_s,
                       key=key if self.dedup else None,
                       route=route, tags=tags)
        tr = trace.active()
        if tr is not None:
            # Trace opened BEFORE the queue insert so the dispatch
            # thread's pop-side spans always find it; the id rides the
            # future so serve.py can stamp X-Trace-Id on the response.
            req.future.trace_id = tr.start_request(
                req.rid, rows=n, deadline_s=deadline_s,
                t0=req.t_enqueue)
        fast = False
        try:
            with self._cond:
                if self._stop:
                    raise RuntimeError("batcher is stopped")
                if self._rows + n > self.queue_depth:
                    if self.metrics is not None:
                        self.metrics.record_reject(n)
                    raise Rejected(
                        f"queue at {self._rows} pending rows; watermark "
                        f"{self.queue_depth} would be exceeded by {n} "
                        "more")
                # The lane decision (ISSUE 14), atomic with admission:
                # empty queue (scheduler.fastlane_eligible) AND a free
                # window slot (try-acquire — the claimed slot is this
                # request's in-flight slot, so the pipeline-depth bound
                # holds across both lanes). Either half failing routes
                # this submit down the ordinary coalescing path.
                if (route is None
                        and fastlane_eligible(self.fastlane, self._rows)
                        and self._slots.acquire(blocking=False)):
                    fast = True
                    with self._inflight_lock:
                        self._inflight += 1
                else:
                    self._q.append(req)
                    self._rows += n
                    self._cond.notify_all()
        except Exception:
            # never admitted: nothing will ever finish this trace
            if tr is not None:
                tr.abort_request(req.rid)
            raise
        if self.controller is not None:
            self.controller.on_arrival(n, now=req.t_enqueue,
                                       coalesced=not fast)
        if fast:
            # Dispatch + fetch + fan-out inline on THIS thread; the
            # returned future is already resolved (or failed). Every
            # path through _fast_dispatch releases the claimed slot
            # and the in-flight count.
            self._fast_dispatch(req)
        return req.future

    def pending_rows(self) -> int:
        with self._cond:
            return self._rows

    def inflight_batches(self) -> int:
        """Dispatch segments popped off the queue whose futures have not
        yet all resolved. DISPATCHED-but-unfetched segments never exceed
        max_inflight (each holds a window slot — the pipeline-depth
        invariant tests assert it engine-side); a split drain's
        not-yet-dispatched segments are counted here too, so
        pending_rows()==0 AND inflight_batches()==0 together still mean
        fully drained."""
        with self._inflight_lock:
            return self._inflight

    # -- autoscale actuation (ISSUE 20) ------------------------------------

    def window(self) -> int:
        """The LIVE in-flight window: the constructed ceiling minus the
        permits apply_scale has parked."""
        with self._cond:
            return self.max_inflight - self._window_parked

    def apply_scale(self, window: Optional[int] = None,
                    max_batch: Optional[int] = None,
                    timeout_s: float = 1.0) -> dict:
        """The single-host actuation surface (ISSUE 20): widen/narrow
        the in-flight window and/or the coalescing bucket ceiling at
        runtime. ONLY the autoscaler's actuator path may call this
        (lint DML019) — a second writer would race the control loop's
        decisions and un-price its cost accounting.

        Window moves by parking/unparking permits on the fixed-capacity
        slot semaphore: narrowing acquires (and holds) permits, widening
        releases held ones — never past the constructed max_inflight
        ceiling, never minting permits. Narrowing waits up to
        `timeout_s` PER PERMIT for in-flight batches to drain; a
        timeout returns the partially-applied window honestly rather
        than blocking the control loop (the next tick retries).

        max_batch moves within the engine's pre-warmed bucket ladder —
        clamped to buckets[-1], so a scale-up amortizes dispatch
        overhead over a fuller batch with ZERO new jit keys (the
        recompiles_after_warmup==0 guarantee is untouched by design).

        Returns {"window": achieved, "max_batch": achieved}.
        """
        if window is not None:
            if window < 1:
                raise ValueError(f"window must be >= 1, got {window}")
            target = min(window, self.max_inflight)
            while True:
                with self._cond:
                    cur = self.max_inflight - self._window_parked
                    if cur < target:          # widen: unpark
                        n = target - cur
                        self._window_parked -= n
                        self._slots.release(n)
                        break
                    if cur == target:
                        break
                # narrow: park one permit at a time OUTSIDE the queue
                # lock (the acquire may wait on a full pipeline; holding
                # _cond across it would stall every submit)
                if not self._slots.acquire(timeout=timeout_s):
                    break                     # partial: report honestly
                with self._cond:
                    self._window_parked += 1
        if max_batch is not None:
            if max_batch < 1:
                raise ValueError(
                    f"max_batch must be >= 1, got {max_batch}")
            with self._cond:
                self.max_batch = min(max_batch, self.engine.buckets[-1])
                if self.controller is not None:
                    # keep the AIMD fill-cap honest about the new ceiling
                    self.controller.max_batch = self.max_batch
        return {"window": self.window(), "max_batch": self.max_batch}

    # -- dispatch side -----------------------------------------------------

    def start(self) -> "DynamicBatcher":
        if self._dispatcher is not None:
            raise RuntimeError("batcher already started")
        if self._stop:
            # A stopped batcher's threads may still be winding down on
            # the shared handle queue; a restart would race them (and
            # submit() is permanently closed anyway). One-shot lifecycle.
            raise RuntimeError(
                "batcher is stopped; construct a new one instead of "
                "restarting")
        self._dispatcher = make_thread(
            target=self._dispatch_loop, name="serve-dispatch", daemon=True)
        self._completer = make_thread(
            target=self._completion_loop, name="serve-complete",
            daemon=True)
        self._dispatcher.start()
        self._completer.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the pipeline; drain=True serves what is already queued
        AND fetches every in-flight batch before returning (every
        accepted future resolves) — including segments a split-dispatch
        cycle has popped off the queue but not yet dispatched: they were
        claimed in-flight at pop time and the dispatch loop finishes the
        whole planned drain before it re-checks for shutdown, so no
        popped request can be stranded (the PR 2 drain hole, audited for
        the batch-former window). drain=False fails still-queued futures
        immediately — in-flight batches are already on the device, so
        their futures still resolve when their fetch lands (the threads
        are daemons; a wedged fetch is abandoned after a short join
        rather than holding stop() hostage)."""
        dropped: list[_Request] = []
        with self._cond:
            self._stop = True
            # Return any autoscale-parked window permits (ISSUE 20):
            # the sanitizer balance-checks the slot semaphore at drain
            # (net zero), and a narrowed window must not throttle the
            # final drain anyway. Stop the Autoscaler BEFORE the
            # batcher so it cannot re-park after this.
            if self._window_parked:
                self._slots.release(self._window_parked)
                self._window_parked = 0
            if not drain:
                while self._q:
                    req = self._q.popleft()
                    self._rows -= req.n
                    dropped.append(req)
            self._cond.notify_all()
        # Futures resolve OUTSIDE the queue lock (lint DML009, the
        # model checker's yield-point audit): a done-callback — the
        # cache front's single-flight fan-out runs inline on whichever
        # thread resolves — must never execute under batcher.queue,
        # where it would stall every concurrent submit and order
        # batcher.queue under whatever locks the callback takes.
        for req in dropped:
            err = RuntimeError("batcher stopped")
            self._finish_trace(req, error=err)
            req.future.set_exception(err)
        timeout = 30 if drain else 1
        for t in (self._dispatcher, self._completer):
            if t is not None:
                t.join(timeout=timeout)
        self._dispatcher = self._completer = None

    def _take_batch(self) -> list[list[_Request]]:
        """Block until there is work, then coalesce: wait until max_batch
        rows are pending or the EFFECTIVE wait (adaptive controller,
        hard-capped at max_wait_us) has elapsed since the OLDEST pending
        request, then pop a prefix of the queue that fits max_batch and
        run it through the batch former. Returns the planned dispatch
        segments — usually one; several when the cost table says split
        beats pad — and [] only when stopping with an empty queue.

        Shed requests are RESOLVED outside the queue lock: failing a
        future (and, traced, recording its spans + finishing its trace)
        under self._cond would stall every concurrent submit() exactly
        when the server is already shedding — the same hygiene the
        metrics snapshot applies to its percentile math.

        Every popped request is claimed in-flight HERE, before the queue
        lock drops: an observer that sees pending_rows()==0 is then
        guaranteed to see ALL of this drain's segments (including the
        not-yet-dispatched ones) in inflight_batches(), so "pending==0
        and inflight==0" really means drained — the bench's open-loop
        drain predicate, and the reason stop(drain=True) cannot lose a
        popped-but-undispatched segment (the PR 2 drain hole, audited
        for the split window).

        Expired-deadline requests (ISSUE 5) are shed HERE, as they are
        popped: their futures fail with DeadlineExceeded (504-fast)
        without ever counting toward the dispatch, so a request whose
        client has already given up costs zero device work — and frees
        its slice of max_batch for requests still worth serving. A pop
        that sheds its entire drain loops back to coalescing instead of
        returning [] (the shutdown signal)."""
        while True:
            with self._cond:
                segments, shed = self._take_batch_locked()
            self._shed_expired(shed)
            if segments is not None:
                return segments

    def _shed_expired(self, shed: list) -> None:
        """Fail the deadline-expired requests popped by one drain
        (504-fast), off the queue lock. Spans + trace finish land
        BEFORE each future resolves — a waiter that has seen the 504
        also sees the finished trace (the Server-Timing contract)."""
        for req, t_shed in shed:
            if self.metrics is not None:
                self.metrics.record_deadline_shed(req.n)
            err = DeadlineExceeded(
                "deadline expired while queued "
                f"({(t_shed - req.deadline) * 1e3:.1f} ms past); "
                "shed before dispatch")
            trace.add_span("queue.wait", req.t_enqueue, t_shed,
                           rids=(req.rid,), shed=True,
                           **(req.tags or {}))
            trace.add_span("deadline.shed", t_shed, t_shed,
                           rids=(req.rid,))
            self._finish_trace(req, error=err)
            req.future.set_exception(err)

    def _take_batch_locked(self) -> tuple:
        """One coalesce-pop-plan cycle under self._cond; returns
        (segments, shed) where segments is None for 'everything popped
        was shed — coalesce again' and shed holds the (request,
        pop-stamp) pairs the CALLER must fail once the lock drops."""
        shed: list = []
        while not self._q and not self._stop:
            self._cond.wait(0.1)
        if not self._q:
            return [], shed
        t_coalesce = time.monotonic()
        # Sample the effective wait when work is actually in hand
        # (the controller may have moved while the queue was idle).
        wait_s = (self.controller.effective_wait_s()
                  if self.controller is not None else self.max_wait_s)
        if self.metrics is not None:
            self.metrics.record_wait(wait_s)
        deadline = self._q[0].t_enqueue + wait_s
        while self._rows < self.max_batch and not self._stop:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self._cond.wait(remaining)
        batch = []
        taken = 0
        now = time.monotonic()
        # Drains are route-uniform (ISSUE 17): one batch runs ONE
        # engine program, so a head pinned to a different route than
        # this drain's first request stays queued for the next cycle.
        route = self._q[0].route
        while (self._q and taken + self._q[0].n <= self.max_batch
               and self._q[0].route == route):
            req = self._q.popleft()
            self._rows -= req.n
            if req.deadline is not None and now >= req.deadline:
                # resolved by the caller AFTER the lock drops
                # (_shed_expired): failing futures and finishing
                # traces under self._cond would stall every
                # concurrent submit
                shed.append((req, now))
                continue
            trace.add_span("queue.wait", req.t_enqueue, now,
                           rids=(req.rid,), **(req.tags or {}))
            taken += req.n
            batch.append(req)
        if not batch:
            return None, shed     # whole drain shed: coalesce again
        t_plan = time.monotonic()
        all_rids = [r.rid for r in batch]
        if self.dedup:
            batch = self._dedup_batch(batch, t_plan)
        segments = self._plan(batch)
        tr = trace.active()
        if tr is not None:
            tr.add_span("batch.coalesce", t_coalesce, now,
                        rids=all_rids, rows=taken)
            tr.add_span("batch.plan", t_plan, time.monotonic(),
                        rids=all_rids, segments=len(segments))
        with self._inflight_lock:
            self._inflight += len(segments)
        return segments, shed

    def _plan(self, batch: list[_Request]) -> list[list[_Request]]:
        """The batch former: cut one drain into bucket-shaped dispatch
        segments per the engine's measured cost table (scheduler.
        plan_segments). No table (stub engines, pre-warmup routers) or
        split=False means one segment — the covering-bucket dispatch."""
        if not batch:
            return []
        counts = [len(batch)]
        if self.split and len(batch) > 1:
            costs_fn = getattr(self.engine, "bucket_costs", None)
            costs = costs_fn() if callable(costs_fn) else None
            if costs:
                counts = plan_segments([r.n for r in batch],
                                       self.engine.buckets, costs)
        segments = []
        off = 0
        for c in counts:
            segments.append(batch[off:off + c])
            off += c
        return segments

    def _dedup_batch(self, batch: list[_Request],
                     now: float) -> list[_Request]:
        """Intra-batch dedup (ISSUE 10): collapse requests with the
        same content hash (and row count — implied by the hash, checked
        anyway) into one representative per drain. Riders are attached
        to their representative's `dups` list and resolved from its
        slice at fan-out, so the dispatched segment carries only unique
        rows — a drain of five identical 4-row requests runs the 4-row
        bucket, not the 32. Rider rids never reach the dispatch
        failpoint (they are not dispatched), so request-sticky fault
        draws and bisection operate on unique rows only."""
        uniques: dict = {}
        out: list[_Request] = []
        dup_rids: list[int] = []
        dup_rows = 0
        for r in batch:
            rep = (uniques.get((r.key, r.n))
                   if r.key is not None else None)
            if rep is not None:
                rep.dups.append(r)
                dup_rids.append(r.rid)
                dup_rows += r.n
                continue
            if r.key is not None:
                uniques[(r.key, r.n)] = r
            out.append(r)
        if dup_rids:
            if self.metrics is not None:
                self.metrics.record_dedup(len(dup_rids), dup_rows)
            trace.add_span("batch.dedup", now, now, rids=dup_rids,
                           collapsed=len(dup_rids))
        return out

    @staticmethod
    def _span_rids(seg: list[_Request]) -> list[int]:
        """Request ids a batch-level trace span covers: the dispatched
        uniques PLUS their dedup riders, so a rider's trace still shows
        the staging/device/fetch stages that produced its bytes. The
        dispatch FAILPOINT keeps unique rids only (riders are never
        dispatched — a sticky fault draw on one would be undispatchable
        and unisolatable)."""
        rids: list[int] = []
        for r in seg:
            rids.append(r.rid)
            rids.extend(d.rid for d in r.dups)
        return rids

    @staticmethod
    def _span_tags(seg: list[_Request]) -> dict:
        """Segment-level attribution for the batch.dispatch span
        (ISSUE 18): the model tag is drain-uniform (one batch runs one
        engine program) so the first tagged request speaks for all;
        tenants can coalesce, so the span carries the sorted distinct
        set. Untagged segments (every pre-tenancy caller) contribute
        nothing — the span shape is unchanged."""
        tags: dict = {}
        tenants = sorted({r.tags["tenant"] for r in seg
                          if r.tags and "tenant" in r.tags})
        if tenants:
            tags["tenants"] = ",".join(tenants)
        for r in seg:
            if r.tags and "model" in r.tags:
                tags["model"] = r.tags["model"]
                break
        return tags

    def _live_version(self) -> Optional[str]:
        """The version a dispatch failure is blamed on: the engine's
        live target (Router) or its own version label (bare engine);
        None (never breaker-counted) when neither exists — e.g. a
        NoLiveModel failure while warming has no version to blame."""
        live_fn = getattr(self.engine, "live_version", None)
        if callable(live_fn):
            return live_fn()
        return getattr(self.engine, "version", None)

    def _finish_trace(self, req: _Request, error=None,
                      t_end: Optional[float] = None) -> None:
        """Close the request's trace (no-op with no tracer). Always
        called BEFORE the future resolves: a client that has seen its
        result/error can immediately read the finished trace. `t_end`
        pins the root's end to a stamp the caller holds (the fast
        lane's completion point)."""
        tr = trace.active()
        if tr is not None:
            tr.finish_request(req.rid, error=error, t_end=t_end)

    def _fail_fanout(self, req: _Request, e: Exception) -> None:
        """Fail one request AND its dedup riders with the same error —
        a rider's bytes were going to come from this request's slice,
        so its outcome is this request's outcome. Traces finish before
        futures resolve, as everywhere."""
        self._finish_trace(req, error=e)
        req.future.set_exception(e)
        for d in req.dups:
            self._finish_trace(d, error=e)
            d.future.set_exception(e)

    def _engine_dispatch(self, seg: list[_Request]):
        """The one engine.dispatch call site, crossed by every first
        dispatch AND every bisection retry: the `batch.dispatch`
        failpoint fires with the segment's request ids, so a
        request-sticky injected fault (serve/faults.py) fails every
        dispatch containing the poison request — and only those."""
        rids = [r.rid for r in seg]
        sp = trace.begin_span("batch.dispatch", rids=self._span_rids(seg),
                              rows=sum(r.n for r in seg),
                              **self._span_tags(seg))
        try:
            # failpoint ctx carries the DISPATCHED rids only: dedup
            # riders are not in this dispatch, so a request-sticky
            # draw cannot poison rows that never reach the engine
            failpoint("batch.dispatch", rids=rids)
            xs = [r.x for r in seg]
            # Segments are route-uniform (_take_batch_locked), so the
            # first request's pin speaks for the whole dispatch;
            # bisection retries re-enter here and inherit it.
            route = seg[0].route
            if route is None:
                return self.engine.dispatch(xs)
            return self.engine.dispatch(xs, infer_dtype=route)
        finally:
            trace.end_span(sp)

    def _fast_dispatch(self, req: _Request) -> None:
        """The bypass lane's whole pipeline, inline on the submitting
        thread (ISSUE 14): dispatch (the engine's resident fast route
        when one fits, the ordinary dispatch otherwise — either way no
        thread hand-offs), the blocking fetch, and the fan-out. The
        caller already holds one window slot and one in-flight count;
        every path out of here releases both. Traces finish BEFORE the
        future resolves, metrics record the same populations a
        coalesced request gets, and failures feed the breaker — the
        lane skips QUEUEING, never observability or resilience."""
        t0 = time.monotonic()
        sp = trace.begin_span("fastpath", rids=(req.rid,), rows=req.n)
        try:
            if sp is not None:
                # admit span ends EXACTLY where the lane span begins:
                # the submit-to-dispatch interval is covered gap-free,
                # so attribution has no bookkeeping residue to hide
                # (the lane's point is proving where microseconds go)
                trace.add_span("fastpath.admit", req.t_enqueue, sp.t0,
                               rids=(req.rid,))
            if req.deadline is not None and t0 >= req.deadline:
                # the pop-time shed, lane edition: submit's entry check
                # ran microseconds ago, but deadline semantics must not
                # depend on which lane a request took — an expired
                # budget is shed at zero device cost here too
                if self.metrics is not None:
                    self.metrics.record_deadline_shed(req.n)
                err = DeadlineExceeded(
                    "deadline expired at fast-lane dispatch "
                    f"({(t0 - req.deadline) * 1e3:.1f} ms past); "
                    "shed before dispatch")
                trace.add_span("deadline.shed", t0, t0,
                               rids=(req.rid,))
                trace.end_span(sp)
                self._finish_trace(req, error=err)
                req.future.set_exception(err)
                with self._inflight_lock:
                    self._inflight -= 1
                self._slots.release()
                return
            try:
                failpoint("batch.dispatch", rids=[req.rid])
                fast = getattr(self.engine, "dispatch_fast", None)
                handle = fast(req.x) if callable(fast) else None
                if handle is None:
                    # lane-contention / no-resident-route fallback:
                    # still on the caller's thread, still queue-free —
                    # only the staging shortcut is declined
                    handle = self.engine.dispatch([req.x])
            except Exception as e:   # singleton cohort: no bisection
                # span closed BEFORE the trace finishes (a span ending
                # after finish_request records to nothing)
                trace.end_span(sp, error=type(e).__name__)
                # _dispatch_failed owns the bookkeeping symmetry: it
                # fails the future, feeds metrics + the breaker, drops
                # the in-flight count and releases the caller's slot.
                self._dispatch_failed([req], e)
                return
            with self._inflight_lock:
                self._dispatched += 1
                depth = self._dispatched
            if self.metrics is not None:
                self.metrics.record_dispatch(time.monotonic() - t0,
                                             inflight=depth)
            # fetch timing stamped HERE, not at lane entry: fetch_ms
            # must measure the same interval on both lanes (the
            # completion loop stamps immediately before its fetch too),
            # or the side-by-side bench comparison reads skewed
            t_fetch = time.monotonic()
            fsp = trace.begin_span("engine.fetch", rids=(req.rid,),
                                   bucket=handle.bucket)
            try:
                logits = self.engine.fetch(handle)
            except Exception as e:
                trace.end_span(fsp, error=type(e).__name__)
                trace.end_span(sp)
                self._fail_fanout(req, e)
                if self.metrics is not None:
                    self.metrics.record_fetch_error(1)
                if self.resilience is not None:
                    self.resilience.record_outcome(
                        getattr(handle, "version", None), ok=False)
                with self._inflight_lock:
                    self._inflight -= 1
                    self._dispatched -= 1
                self._slots.release()
                return
            finally:
                trace.end_span(fsp)
            t_done = time.monotonic()
            version = getattr(handle, "version", None)
            if self.resilience is not None:
                self.resilience.record_outcome(version, ok=True)
            if self.controller is not None:
                self.controller.on_latency(t_done - req.t_enqueue)
            req.future.version = version
            # the lane span must close BEFORE the trace finishes (the
            # finally's end is then an idempotent no-op): attribution
            # reads only spans recorded into the still-live trace. The
            # root's end is pinned to THIS stamp — the span's own end
            # lands at-or-after it, so the lane request's wall clock is
            # covered gap-free and attribution carries no bookkeeping
            # residue (the leg's >= 0.95 bar is about exactly that).
            t_end = time.monotonic()
            trace.end_span(sp)
            self._finish_trace(req, t_end=t_end)
            req.future.set_result(logits[:req.n])
            if self.metrics is not None:
                self.metrics.record_fastpath(req.n)
                self.metrics.record_fetch(t_done - t_fetch)
                self.metrics.record_batch(
                    rows=req.n, bucket=handle.bucket,
                    queue_depth=self.pending_rows(), version=version,
                    replica=getattr(handle, "replica", None),
                    infer_dtype=getattr(handle, "infer_dtype", None))
                self.metrics.record_latency(t_done - req.t_enqueue,
                                            rows=req.n, version=version)
            with self._inflight_lock:
                self._inflight -= 1
                self._dispatched -= 1
            self._slots.release()
        finally:
            trace.end_span(sp)

    def _wait_for_work(self) -> bool:
        """Park until the queue is non-empty (True) or the batcher is
        stopping with nothing queued (False) — WITHOUT holding a
        window slot. The old loop acquired its slot before this wait,
        which meant an idle max_inflight=1 pipeline kept its only slot
        hostage and the fast lane's try-acquire could never succeed;
        the slot is now claimed only once there is work to coalesce,
        which preserves the accumulate-while-full property (the
        acquire still precedes the pop) without starving the lane."""
        with self._cond:
            while not self._q and not self._stop:
                self._cond.wait(0.1)
            return bool(self._q)

    def _dispatch_loop(self) -> None:
        while True:
            if not self._wait_for_work():
                self._handles.put(None)      # completion shutdown
                return
            # Acquire the window slot BEFORE coalescing: while the
            # window is full, arriving requests keep accumulating toward
            # a fuller batch instead of being split across dispatches.
            self._slots.acquire()
            segments = self._take_batch()
            if not segments:
                self._slots.release()
                self._handles.put(None)      # completion shutdown
                return
            t_pop = time.monotonic()
            for i, seg in enumerate(segments):
                if i:
                    # Later segments of a split drain each hold their
                    # own window slot too (the completion thread frees
                    # slots as earlier batches fan out, so this cannot
                    # deadlock even at max_inflight=1) — the in-flight
                    # bound stays an engine-side invariant under splits.
                    self._slots.acquire()
                t0 = time.monotonic()
                if trace.active() is not None:
                    # pop -> this segment's dispatch begin: plan +
                    # bookkeeping, plus the window-slot wait for later
                    # segments of a split drain — without it that gap
                    # would be unattributed residue
                    trace.add_span("batch.pending", t_pop, t0,
                                   rids=[r.rid for r in seg])
                try:
                    handle = self._engine_dispatch(seg)
                except Exception as e:   # fail/bisect, keep serving
                    # the failure path resolves every future in the
                    # segment (culprit errors, cohort-mate retries)
                    # BEFORE the segment leaves the in-flight count —
                    # same drain invariant as the completion loop;
                    # remaining segments still dispatch
                    self._dispatch_failed(seg, e)
                    continue
                # t_disp rides the handle queue: the completion thread
                # synthesizes the dispatched-but-unfetched window as an
                # `engine.enqueued` span from it (the ISSUE 2 overlap,
                # visible per batch in the exported trace)
                t_disp = time.monotonic()
                with self._inflight_lock:
                    self._dispatched += 1
                    depth = self._dispatched
                if self.metrics is not None:
                    self.metrics.record_dispatch(t_disp - t0,
                                                 inflight=depth)
                self._handles.put((seg, handle, t_disp))

    def _dispatch_failed(self, seg: list[_Request], e: Exception) -> None:
        """A dispatched segment raised before reaching the device queue.
        Without a resilience policy (or for a single-request segment,
        where the culprit IS the segment) the whole cohort fails — the
        PR 1-4 behavior. With bisection enabled, the segment is retried
        as recursively split halves along request boundaries: a poison
        request deterministically re-fails every sub-dispatch that
        contains it, so the recursion bottoms out failing ONLY the
        culprit's singleton while every cohort-mate's sub-segment
        dispatches clean. Sub-segments are smaller than the original,
        so their covering buckets are existing ladder rungs — isolation
        reuses compiled programs, never new shapes (the chaos bench
        asserts recompiles stay 0 through a fault storm).

        Accounting: the caller's segment holds one in-flight count and
        one window slot. The first successfully dispatched sub-segment
        inherits them; each further one acquires its own slot (the
        completion thread frees slots as it drains, so this cannot
        deadlock even at max_inflight=1 — the split-drain argument). If
        every sub-dispatch fails, the parent's count and slot are
        released here.

        Dispatch failures feed the circuit breaker too: the routed
        version is unknown (the exception aborted before a handle
        existed), so the failure is attributed to the version that
        WOULD have served it — the live one. An engine that dies at
        dispatch() must be able to trip the breaker exactly like one
        that dies at fetch()."""
        res = self.resilience
        # 503-shaped errors (NoLiveModel while warming/draining) are
        # SYSTEMIC sheds, not request faults: splitting the segment
        # would re-raise identically on every sub-dispatch — O(n)
        # futile retries whose singleton failures would then masquerade
        # as "isolated poison" in the telemetry. They also blame no
        # version (nothing was live to blame).
        systemic = getattr(e, "status", None) == 503
        bisect = (res is not None and res.bisect and len(seg) > 1
                  and not systemic)
        ndups = sum(len(r.dups) for r in seg)
        if not bisect:
            if self.metrics is not None:
                if (not systemic and res is not None and res.bisect
                        and len(seg) == 1):
                    # a singleton failing at dispatch IS an isolated
                    # culprit (no cohort to protect); its dedup riders
                    # fail alongside it as plain dispatch errors
                    self.metrics.record_poison_isolated(seg[0].n)
                    if ndups:
                        self.metrics.record_dispatch_error(ndups)
                else:
                    self.metrics.record_dispatch_error(len(seg) + ndups)
            for r in seg:
                self._fail_fanout(r, e)
            if res is not None and not systemic:
                res.record_outcome(self._live_version(), ok=False,
                                   n=len(seg))
            with self._inflight_lock:
                self._inflight -= 1
            self._slots.release()
            return
        if self.metrics is not None:
            self.metrics.record_bisect_split()
        mid = len(seg) // 2
        t_split = time.monotonic()
        trace.add_span("bisect.split", t_split, t_split,
                       rids=[r.rid for r in seg],
                       into=[mid, len(seg) - mid])
        pending: deque = deque([seg[:mid], seg[mid:]])
        enqueued = 0
        while pending:
            sub = pending.popleft()
            sub_err = None
            sp = trace.begin_span("bisect.dispatch",
                                  rids=self._span_rids(sub),
                                  rows=sum(r.n for r in sub))
            try:
                handle = self._engine_dispatch(sub)
            except Exception as se:
                sub_err = se
                handle = None
            finally:
                trace.end_span(sp, error=(type(sub_err).__name__
                                          if sub_err is not None
                                          else None))
            if sub_err is not None:
                if len(sub) == 1:
                    if self.metrics is not None:
                        self.metrics.record_poison_isolated(sub[0].n)
                        if sub[0].dups:
                            self.metrics.record_dispatch_error(
                                len(sub[0].dups))
                    self._fail_fanout(sub[0], sub_err)
                    if res is not None:
                        res.record_outcome(self._live_version(),
                                           ok=False)
                else:
                    if self.metrics is not None:
                        self.metrics.record_bisect_split()
                    m = len(sub) // 2
                    t_split = time.monotonic()
                    trace.add_span("bisect.split", t_split, t_split,
                                   rids=[r.rid for r in sub],
                                   into=[m, len(sub) - m])
                    # left half first: FIFO order is preserved across
                    # the completion thread's in-order fetches
                    pending.appendleft(sub[m:])
                    pending.appendleft(sub[:m])
                continue
            if enqueued:
                self._slots.acquire()
                with self._inflight_lock:
                    self._inflight += 1
            with self._inflight_lock:
                self._dispatched += 1
            if self.metrics is not None:
                self.metrics.record_bisect_rescued(
                    len(sub), sum(r.n for r in sub))
            self._handles.put((sub, handle, time.monotonic()))
            enqueued += 1
        if not enqueued:
            with self._inflight_lock:
                self._inflight -= 1
            self._slots.release()

    def _completion_loop(self) -> None:
        while True:
            item = self._handles.get()
            if item is None:
                return
            batch, handle, t_disp = item
            t0 = time.monotonic()
            rids = self._span_rids(batch)
            # The in-flight window this batch just spent dispatched-
            # but-unfetched: device compute overlapping later batches'
            # staging (ISSUE 2). Synthesized from stamps both threads
            # already hold, so no span crosses the thread hop open.
            trace.add_span("engine.enqueued", t_disp, t0, rids=rids,
                           tid="inflight-window", bucket=handle.bucket)
            sp = trace.begin_span("engine.fetch", rids=rids,
                                  bucket=handle.bucket)
            try:
                logits = self.engine.fetch(handle)
            except Exception as e:   # fan the failure out, keep serving
                # the span must be recorded (with the error) BEFORE the
                # traces finish, or the failed requests' exemplars would
                # miss their fetch stage; the finally's end is then a
                # no-op (end_span is idempotent)
                trace.end_span(sp, error=type(e).__name__)
                for r in batch:
                    self._fail_fanout(r, e)
                if self.metrics is not None:
                    self.metrics.record_fetch_error(
                        sum(1 + len(r.dups) for r in batch))
                if self.resilience is not None:
                    # a fetch failure is attributable: the handle knows
                    # which version computed (and failed) the batch —
                    # the circuit breaker's per-version failure signal
                    self.resilience.record_outcome(
                        getattr(handle, "version", None), ok=False,
                        n=len(batch))
                with self._inflight_lock:
                    self._inflight -= 1
                    self._dispatched -= 1
                self._slots.release()
                continue
            finally:
                trace.end_span(sp)
            t_done = time.monotonic()
            version = getattr(handle, "version", None)
            if self.resilience is not None:
                self.resilience.record_outcome(version, ok=True,
                                               n=len(batch))
            if self.controller is not None:
                # Feed the AIMD controller every request's end-to-end
                # latency — violations step the effective wait down
                # before this batch's futures even resolve. Dedup
                # riders count too: their latency is as real as their
                # representative's.
                for r in batch:
                    self.controller.on_latency(t_done - r.t_enqueue)
                    for d in r.dups:
                        self.controller.on_latency(t_done - d.t_enqueue)
            off = 0
            for r in batch:
                # Attribution rides the future itself (set BEFORE
                # set_result, so a waiter that has seen the result also
                # sees the tag): serve.py reports which model version
                # actually computed THIS request — under canary routing
                # that is not necessarily the live version. The trace
                # finishes first for the same reason: the Server-Timing
                # breakdown must be readable the moment result() is.
                r.future.version = version
                # fan-out wait [fetch done -> this resolve] closed per
                # request, so attribution's residue stays the true
                # unexplained remainder, not bookkeeping time
                trace.add_span("batch.fanout", t_done, time.monotonic(),
                               rids=(r.rid,))
                self._finish_trace(r)
                r.future.set_result(logits[off:off + r.n])
                # Dedup riders (ISSUE 10): identical rows that rode
                # this request instead of dispatching — same version
                # tag and fate, but their OWN copy of the slice: the
                # representative and its riders alias the same rows,
                # so sharing the view would let one caller's in-place
                # edit corrupt another's response (per-request slices
                # of a normal batch are disjoint; these are not).
                for d in r.dups:
                    d.future.version = version
                    trace.add_span("batch.fanout", t_done,
                                   time.monotonic(), rids=(d.rid,))
                    self._finish_trace(d)
                    d.future.set_result(logits[off:off + r.n].copy())
                off += r.n
            if self.metrics is not None:
                rows = sum(r.n for r in batch)
                # Same version tag (serve/registry.py labels): the
                # canary population's metrics separate from the live
                # population's. Bare-engine handles tag None (untagged).
                self.metrics.record_fetch(t_done - t0)
                # The replica tag (fleet handles only) names the replica
                # that COMPUTED the batch — after a failover rescue that
                # is the sibling, not the replica originally picked.
                self.metrics.record_batch(
                    rows=rows, bucket=handle.bucket,
                    queue_depth=self.pending_rows(), version=version,
                    replica=getattr(handle, "replica", None),
                    infer_dtype=getattr(handle, "infer_dtype", None))
                for r in batch:
                    self.metrics.record_latency(t_done - r.t_enqueue,
                                                rows=r.n, version=version)
                    for d in r.dups:
                        self.metrics.record_latency(
                            t_done - d.t_enqueue, rows=d.n,
                            version=version)
            # A batch leaves the in-flight count (and frees its window
            # slot) only AFTER its futures resolved and its metrics
            # landed: inflight_batches()==0 with an empty queue then
            # proves every accepted request is fully served — the drain
            # invariant the bench and stop() rely on.
            with self._inflight_lock:
                self._inflight -= 1
                self._dispatched -= 1
            self._slots.release()
