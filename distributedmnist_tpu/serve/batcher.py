"""Dynamic micro-batcher: coalesce concurrent requests into engine-sized
batches, with bounded-queue backpressure.

A single MNIST forward is ~microseconds of device time; serving requests
one-at-a-time would be dispatch-bound exactly the way unfused training
steps were (SURVEY.md §7.3). The batcher holds a thread-safe queue of
pending requests and a single dispatch thread that coalesces whatever is
waiting — up to `max_batch` rows or `max_wait_us` after the oldest
request arrived, whichever comes first — into one engine.infer() call
(which pads to the covering bucket), then fans the sliced results back
out to per-request futures. Latency-throughput tradeoff in two knobs:
`max_wait_us` bounds the queueing delay a lone request can suffer;
`max_batch` bounds how much traffic one dispatch can absorb.

Backpressure: admission is bounded by `queue_depth` PENDING rows. Beyond
the watermark submit() raises Rejected (HTTP 503 semantics — serve.py
maps it to exactly that) instead of letting queue delay grow without
bound: under overload a closed feedback to the client keeps the p99 of
ACCEPTED requests near the service time, where an unbounded queue would
melt every request's latency together (the Clipper/Clockwork admission
argument — PAPERS.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class Rejected(RuntimeError):
    """Queue past its watermark: shed this request (503 semantics)."""

    status = 503


@dataclass
class _Request:
    x: np.ndarray                 # (n, 28, 28, 1) uint8
    n: int
    t_enqueue: float              # time.monotonic()
    future: Future = field(default_factory=Future)


class DynamicBatcher:
    """Single dispatch thread over a bounded request queue.

    start()/stop() manage the thread; submit(x) -> Future resolving to
    the request's (n, 10) logits. All engine calls happen on the one
    dispatch thread, so the engine itself needs no locking.
    """

    def __init__(self, engine, max_batch: Optional[int] = None,
                 max_wait_us: int = 1000,
                 queue_depth: int = 4096, metrics=None):
        self.engine = engine
        self.max_batch = min(max_batch or engine.max_batch,
                             engine.buckets[-1])
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_wait_s = max_wait_us / 1e6
        self.queue_depth = queue_depth
        self.metrics = metrics
        self._q: deque[_Request] = deque()
        self._rows = 0                   # pending rows, watermark basis
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- client side -------------------------------------------------------

    def submit(self, x) -> Future:
        """Enqueue up to max_batch rows; Future resolves to their logits.
        Raises Rejected past the queue watermark (overload shedding) and
        ValueError for requests no single dispatch could ever carry."""
        x = self.engine._as_images(x)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} rows exceeds max_batch={self.max_batch};"
                " split it client-side")
        req = _Request(x=x, n=n, t_enqueue=time.monotonic())
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is stopped")
            if self._rows + n > self.queue_depth:
                if self.metrics is not None:
                    self.metrics.record_reject(n)
                raise Rejected(
                    f"queue at {self._rows} pending rows; watermark "
                    f"{self.queue_depth} would be exceeded by {n} more")
            self._q.append(req)
            self._rows += n
            self._cond.notify_all()
        return req.future

    def pending_rows(self) -> int:
        with self._cond:
            return self._rows

    # -- dispatch side -----------------------------------------------------

    def start(self) -> "DynamicBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the dispatch thread; drain=True serves what is already
        queued first, drain=False fails pending futures."""
        with self._cond:
            self._stop = True
            if not drain:
                while self._q:
                    req = self._q.popleft()
                    self._rows -= req.n
                    req.future.set_exception(
                        RuntimeError("batcher stopped"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _take_batch(self) -> list[_Request]:
        """Block until there is work, then coalesce: wait until max_batch
        rows are pending or max_wait has elapsed since the OLDEST pending
        request, then pop a prefix of the queue that fits max_batch.
        Returns [] only when stopping with an empty queue."""
        with self._cond:
            while not self._q and not self._stop:
                self._cond.wait(0.1)
            if not self._q:
                return []
            deadline = self._q[0].t_enqueue + self.max_wait_s
            while self._rows < self.max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = []
            taken = 0
            while self._q and taken + self._q[0].n <= self.max_batch:
                req = self._q.popleft()
                taken += req.n
                batch.append(req)
            self._rows -= taken
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            rows = sum(r.n for r in batch)
            try:
                x = (batch[0].x if len(batch) == 1
                     else np.concatenate([r.x for r in batch]))
                logits = self.engine.infer(x)
            except Exception as e:   # fan the failure out, keep serving
                for r in batch:
                    r.future.set_exception(e)
                continue
            t_done = time.monotonic()
            off = 0
            for r in batch:
                r.future.set_result(logits[off:off + r.n])
                off += r.n
            if self.metrics is not None:
                self.metrics.record_batch(
                    rows=rows, bucket=self.engine.bucket_for(rows),
                    queue_depth=self.pending_rows())
                for r in batch:
                    self.metrics.record_latency(t_done - r.t_enqueue,
                                                rows=r.n)
