"""Low-precision inference fast path: weight quantization + the
inference-specialized forwards the bf16/int8 serving engines run.

The float32 serving engine intentionally runs the TRAINING forward —
same model object, same numerics as eval, which is what makes it the
parity oracle (shadow comparisons, the registry's variant gate) and
keeps checkpoints bit-faithful. This module builds the other half of the
Clipper "model optimization" layer (PAPERS.md): a forward specialized
for inference-only execution, in a lower precision, that a variant may
serve ONLY after the registry's accuracy-parity gate passes
(serve/registry.py; thresholds in PARITY.md).

What the fast path changes relative to the training forward:

- **weight quantization (int8)**: per-output-channel symmetric scales
  computed once at load — scale[j] = max|W[:, j]| / 127, Wq = round(W /
  scale) — for every dense AND conv kernel. The quantization round-trip
  is baked into whatever the compute route uses, so the parity gate
  always measures the real accuracy cost.
- **folded input normalization**: the training forward computes
  x.astype(dtype)/255 over every pixel of every batch; inference folds
  the 1/255 into the first layer's scales at load, so the hot path casts
  and multiplies nothing it doesn't have to.
- **inference conv route**: convs run as im2col patch matmuls
  (ops/conv.py) on every platform — GEMMs are the fast path on the MXU
  *and* on this repo's CPU bench host (measured ~1.5-3x over the lax
  conv lowering at serving batch sizes); training keeps lax convs on CPU
  because that choice is about the BACKWARD pass, which serving never
  runs.
- **fused dense epilogues**: the dense+bias+relu chain goes through
  ops/fused.py's forward-only inference ops — the Pallas kernel on TPU
  (int8 x int8 -> int32 with the f32 dequant epilogue fused), interpret
  mode for CPU tests, plain XLA on the CPU serving path (XLA CPU has no
  fast integer GEMM, so the int8 engine dequantizes its int8 weights
  once at build there and runs f32 GEMMs over quantization-round-tripped
  values — weight-only quantization, the W8A32 scheme).

Compute routes by (infer_dtype, resolved fused mode):

| dtype    | XLA (CPU serving)                | PALLAS / PALLAS_INTERPRET     |
|----------|----------------------------------|-------------------------------|
| bfloat16 | bf16 GEMMs, f32 logits           | fused bf16 dense+relu kernel  |
| int8     | dequantized-at-build f32 GEMMs   | int8 MXU dense stack, STATIC  |
|          | (weights round-tripped via int8) | calibrated activation scales  |

int8 activation scales are STATIC (ISSUE 17 satellite): calibrated once
at variant build by running the held-out calibration batch (the
registry's parity images plus a seeded dense-random probe block) through
a pure-numpy replica of each pre-quantization stage, taking max|h| with
25% headroom. The per-dispatch max-reduction the dynamic scheme paid on
every batch disappears from the hot path, the quantization error becomes
batch-independent (a row's logits no longer depend on its batchmates'
dynamic range — the cascade's byte-stability tests rely on this), and
the parity gate re-measures the accuracy cost of the fixed scales
(PARITY.md). Calibration is host-side numpy only: building a variant
from ABSTRACT params (the compile-surface auditor does) stays free of
device work, and the prepared scale is a 0-d f32 array leaf, so it rides
the jit trace as a value-independent operand — no new cache keys.

prepare_inference() is the single entry point: it returns the prepared
parameter pytree (device_put-able) plus a pure forward(params, x_u8) ->
f32 logits the engine jits exactly like the training-precision one.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

INFER_DTYPES = ("float32", "bfloat16", "int8", "megakernel")

# The whole-net fused-inference variant (ISSUE 14): full f32 numerics
# served through ONE ops/fused.py megakernel call per dispatch instead
# of the per-layer chain — a KERNEL variant, not a precision, but it
# rides the same variant machinery (registry warm + zero-compile
# prove-it + parity gate, router labels, cache keys, by_dtype metrics)
# because that machinery is exactly what "an alternative compiled
# forward that must prove itself before taking traffic" needs. Only
# models listed here have a megakernel; the registry and the static
# auditor (analysis/jaxcheck.py) both consult variant_supported so an
# unsupported model's auto-activation skips it instead of failing it.
MEGAKERNEL = "megakernel"
MEGAKERNEL_MODELS = ("mlp",)


def variant_supported(model, infer_dtype: str) -> bool:
    """Whether `model` (a models.* instance or its config name) can
    build the `infer_dtype` variant at all — the megakernel exists for
    the MLP only; every other dtype is model-agnostic."""
    if infer_dtype != MEGAKERNEL:
        return infer_dtype in INFER_DTYPES
    if isinstance(model, str):
        return model in MEGAKERNEL_MODELS
    from distributedmnist_tpu import models

    return isinstance(model, models.MLP)


def quantize_channelwise(w) -> tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric int8 quantization of a dense (in,
    out) or conv (kh, kw, cin, out) kernel: scale[j] = max|W[..., j]| /
    127 (an all-zero channel gets scale 1.0 so dequant stays exact),
    Wq = clip(round(W / scale), -127, 127). Returns (int8 values,
    float32 per-channel scales)."""
    w = np.asarray(w, dtype=np.float32)
    if w.ndim < 2:
        raise ValueError(
            f"channelwise quantization wants a >=2-D kernel, got shape "
            f"{w.shape}")
    flat = w.reshape(-1, w.shape[-1])
    scale = np.max(np.abs(flat), axis=0) / 127.0
    scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize(q, scale) -> np.ndarray:
    """The quantization round-trip's float side: q * scale, float32."""
    return np.asarray(q, dtype=np.float32) * np.asarray(scale,
                                                        dtype=np.float32)


def quantize_act(h):
    """Dynamic per-dispatch activation quantization (traced, static
    shapes): one symmetric scale over the whole activation tensor.
    Returns (int8 values, the f32 scalar scale). No serving route uses
    this anymore (static calibrated scales, below) — kept as the
    reference rule the calibration's headroom is judged against."""
    import jax.numpy as jnp

    s = jnp.maximum(jnp.max(jnp.abs(h)) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(h / s), -127, 127).astype(jnp.int8)
    return q, s


def quantize_act_static(h, scale):
    """Static-scale activation quantization (traced): clip/round by the
    CALIBRATED scalar baked into the prepared params at build — no
    per-dispatch max-reduction, and a row's quantization error never
    depends on its batchmates' dynamic range."""
    import jax.numpy as jnp

    return jnp.clip(jnp.round(h / scale), -127, 127).astype(jnp.int8)


# Headroom multiplier on the calibration batch's max|activation|: the
# fixed scale must cover inputs denser than any calibration row without
# clipping into wrong-argmax territory, at the cost of ~1.25x coarser
# quantization steps — which the parity gate re-measures (PARITY.md).
ACT_CALIBRATION_HEADROOM = 1.25

# Rows of seeded uniform-random uint8 images appended to the held-out
# calibration batch: all-dense worst-case pixels the MNIST-like images
# never produce, so the calibrated scales cover the random-input parity
# probes (tests) and adversarial traffic, not just digit sparsity.
_CALIB_PROBE_ROWS = 32


def calibration_batch(rows: int = 128) -> np.ndarray:
    """The activation-calibration inputs: the registry's held-out parity
    batch (same seed + distribution the cascade/parity gates measure on)
    concatenated with the seeded dense-random probe block."""
    from distributedmnist_tpu.data import synthetic_mnist
    from distributedmnist_tpu.serve.registry import PARITY_SEED

    data = synthetic_mnist(seed=PARITY_SEED, train_n=16, test_n=rows)
    held = np.asarray(data["test_x"][:rows], dtype=np.uint8)
    rng = np.random.default_rng(PARITY_SEED)
    probe = rng.integers(0, 256,
                         size=(_CALIB_PROBE_ROWS,) + held.shape[1:],
                         dtype=np.uint8)
    return np.concatenate([held, probe], axis=0)


def _static_act_scale(h_abs_max: float) -> np.ndarray:
    """The calibrated scale as a 0-d f32 array leaf (a jit operand,
    value-independent — no new compile-cache keys)."""
    s = max(float(h_abs_max) * ACT_CALIBRATION_HEADROOM / 127.0, 1e-8)
    return np.asarray(s, dtype=np.float32)


def _mlp_weights(params) -> tuple:
    """(w1, b1, w2, b2) from either MLP param layout: the nn.Dense tree
    ({'hidden': {kernel, bias}}) or the fused-Pallas flat leaves
    ({'hidden_kernel', 'hidden_bias'} — models/mlp.py)."""
    if "hidden" in params:
        w1, b1 = params["hidden"]["kernel"], params["hidden"]["bias"]
    else:
        w1, b1 = params["hidden_kernel"], params["hidden_bias"]
    return (np.asarray(w1, np.float32), np.asarray(b1, np.float32),
            np.asarray(params["logits"]["kernel"], np.float32),
            np.asarray(params["logits"]["bias"], np.float32))


def _center_pixels(x_u8):
    """uint8 pixels -> int8 by centering at 128 (the int8 matmul's
    operand range). The +128 offset term is linear in the weights, so
    callers fold 128 * colsum(Wq) * scale into the layer bias at load —
    the kernel never sees it."""
    import jax.numpy as jnp

    return (x_u8.astype(jnp.int32) - 128).astype(jnp.int8)


def _prepare_mlp(params, infer_dtype: str, mode: str, calib_x=None):
    import jax.numpy as jnp

    from distributedmnist_tpu.ops import fused

    w1, b1, w2, b2 = _mlp_weights(params)
    if infer_dtype == "bfloat16":
        prep = {"w1": (w1 / 255.0).astype(jnp.bfloat16),
                "b1": b1.astype(jnp.bfloat16),
                "w2": w2.astype(jnp.bfloat16),
                "b2": b2.astype(jnp.bfloat16)}

        def forward(p, x_u8):
            x = x_u8.reshape(x_u8.shape[0], -1).astype(jnp.bfloat16)
            h = fused.dense_relu_inference(x, p["w1"], p["b1"], mode)
            return (h @ p["w2"]).astype(jnp.float32) \
                + p["b2"].astype(jnp.float32)

        return prep, forward

    q1, s1 = quantize_channelwise(w1)
    q2, s2 = quantize_channelwise(w2)
    if mode == fused.XLA:
        # No fast integer GEMM on this route: bake the round-trip in at
        # load and run f32 (weight-only quantization).
        prep = {"w1": dequantize(q1, s1) / 255.0, "b1": b1,
                "w2": dequantize(q2, s2), "b2": b2}

        def forward(p, x_u8):
            x = x_u8.reshape(x_u8.shape[0], -1).astype(jnp.float32)
            h = fused.dense_relu_inference(x, p["w1"], p["b1"],
                                           fused.XLA)
            return h @ p["w2"] + p["b2"]

        return prep, forward

    # Pallas route: true int8 x int8 -> int32 dense stack. Pixels center
    # to int8; the +128 offset folds into the first bias.
    s1_eff = (s1 / 255.0).astype(np.float32)
    b1_eff = (b1 + 128.0 * q1.astype(np.float32).sum(axis=0) * s1_eff)
    prep = {"w1q": q1, "s1": s1_eff, "b1": b1_eff.astype(np.float32),
            "w2q": q2, "s2": s2, "b2": b2}
    # Static activation calibration (ISSUE 17 satellite): replicate the
    # layer-1 forward in numpy over the calibration batch — the int8
    # matmul is exact in both worlds, so max|h| here IS the traced
    # route's — and bake the hidden activation's scale into the tree.
    calib = (np.asarray(calib_x, dtype=np.uint8)
             if calib_x is not None else calibration_batch())
    xc = calib.reshape(calib.shape[0], -1).astype(np.int32) - 128
    h = np.maximum(
        (xc @ q1.astype(np.int32)).astype(np.float32) * s1_eff + b1_eff,
        0.0)
    prep["act_scale"] = _static_act_scale(np.max(np.abs(h)))

    def forward(p, x_u8):
        x = _center_pixels(x_u8.reshape(x_u8.shape[0], -1))
        h = fused.quant_dense(x, p["w1q"], p["s1"], p["b1"],
                              relu=True, mode=mode)
        hq = quantize_act_static(h, p["act_scale"])
        return fused.quant_dense(hq, p["w2q"],
                                 p["s2"] * p["act_scale"], p["b2"],
                                 relu=False, mode=mode)

    return prep, forward


def _prepare_mlp_megakernel(params, mode: str):
    """The whole-net fused-inference forward (ISSUE 14): float32
    numerics, /255 folded into the first layer's weights at load (the
    quantized variants' trick, applied at full precision), and the
    entire dense stack dispatched as ONE ops/fused.py megakernel call
    — the per-dispatch overhead of the layer chain collapses to a
    single kernel launch, which is where single-request latency lives.
    On the XLA route (CPU serving) the 'kernel' is the jnp oracle XLA
    fuses; PALLAS/PALLAS_INTERPRET run the real single pallas_call."""
    import jax.numpy as jnp

    from distributedmnist_tpu.ops import fused

    w1, b1, w2, b2 = _mlp_weights(params)
    prep = {"w1": (w1 / 255.0).astype(np.float32), "b1": b1,
            "w2": w2, "b2": b2}

    def forward(p, x_u8):
        x = x_u8.reshape(x_u8.shape[0], -1).astype(jnp.float32)
        return fused.mlp_megakernel(x, p["w1"], p["b1"], p["w2"],
                                    p["b2"], mode)

    return prep, forward


def _np_im2col_conv(x, kernel, bias, padding: str) -> np.ndarray:
    """Numpy replica of ops/conv.im2col_conv (NHWC, stride 1) for the
    activation-calibration pass: same shifted-slice accumulation, same
    SAME-pad rule — so the calibrated max|h| is measured on the exact
    tensors the traced route produces."""
    kh, kw, cin, feat = kernel.shape
    if padding == "SAME":
        x = np.pad(x, ((0, 0), (kh // 2, kh // 2),
                       (kw // 2, kw // 2), (0, 0)))
    n, h, w, c = x.shape
    oh, ow = h - kh + 1, w - kw + 1
    out = np.zeros((n, oh, ow, feat), dtype=np.float32)
    for i in range(kh):
        for j in range(kw):
            out += x[:, i:i + oh, j:j + ow, :] @ kernel[i, j]
    return out + bias


def _np_avg_pool2(x) -> np.ndarray:
    n, h, w, c = x.shape
    return x.reshape(n, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))


def _prepare_lenet(params, infer_dtype: str, mode: str, calib_x=None):
    import jax.numpy as jnp

    from distributedmnist_tpu.ops import fused
    from distributedmnist_tpu.ops.conv import avg_pool2, im2col_conv

    names = ("conv1", "conv2", "fc1", "fc2", "logits")
    W = {n: np.asarray(params[n]["kernel"], np.float32) for n in names}
    B = {n: np.asarray(params[n]["bias"], np.float32) for n in names}

    if infer_dtype == "bfloat16":
        prep = {n: {"kernel": (W[n] / (255.0 if n == "conv1" else 1.0))
                    .astype(jnp.bfloat16),
                    "bias": B[n].astype(jnp.bfloat16)} for n in names}
    else:
        # int8: every kernel quantized; the compute route below decides
        # whether the int8 values or their round-tripped f32 side run.
        # conv1's scales absorb the 1/255 input normalization.
        prep = {}
        for n in names:
            q, s = quantize_channelwise(W[n])
            if n == "conv1":
                s = (s / 255.0).astype(np.float32)
            prep[n] = {"q": q, "scale": s, "bias": B[n]}

    quant_dense_stack = infer_dtype == "int8" and mode != fused.XLA
    if infer_dtype == "int8":
        # Convs always run as f32 patch matmuls over the round-tripped
        # weights (pooling intermediates are float regardless); the
        # dense stack is where the int8 MXU route lives.
        for n in ("conv1", "conv2"):
            prep[n]["kernel"] = dequantize(prep[n].pop("q"),
                                           prep[n].pop("scale"))
        if not quant_dense_stack:
            for n in ("fc1", "fc2", "logits"):
                prep[n]["kernel"] = dequantize(prep[n].pop("q"),
                                               prep[n].pop("scale"))

    if quant_dense_stack:
        # Static activation calibration (ISSUE 17 satellite): push the
        # calibration batch through a numpy replica of the conv trunk
        # (the prep kernels are already round-tripped f32, so these ARE
        # the traced route's tensors), then propagate through the
        # quantized dense stack stage by stage — each stage's scale is
        # calibrated on the previous stage's QUANTIZED output, exactly
        # the distribution it sees at serving time.
        calib = (np.asarray(calib_x, dtype=np.uint8)
                 if calib_x is not None else calibration_batch())
        x = calib.astype(np.float32)           # /255 folded in conv1
        x = _np_im2col_conv(x, prep["conv1"]["kernel"],
                            prep["conv1"]["bias"], "SAME")
        x = _np_avg_pool2(np.maximum(x, 0.0))
        x = _np_im2col_conv(x, prep["conv2"]["kernel"],
                            prep["conv2"]["bias"], "VALID")
        x = _np_avg_pool2(np.maximum(x, 0.0))
        x = x.reshape(x.shape[0], -1).astype(np.float32)
        for n in ("fc1", "fc2", "logits"):
            s = _static_act_scale(np.max(np.abs(x)))
            prep[n]["act_scale"] = s
            xq = np.clip(np.round(x / float(s)), -127.0, 127.0)
            acc = (xq.astype(np.int32)
                   @ prep[n]["q"].astype(np.int32)).astype(np.float32)
            x = acc * (prep[n]["scale"] * float(s)) + prep[n]["bias"]
            if n != "logits":
                x = np.maximum(x, 0.0)

    act = jnp.bfloat16 if infer_dtype == "bfloat16" else jnp.float32
    dense_mode = mode if infer_dtype == "bfloat16" else (
        fused.XLA if not quant_dense_stack else mode)

    def forward(p, x_u8):
        x = x_u8.astype(act)                       # /255 folded in conv1
        x = im2col_conv(x, p["conv1"]["kernel"], p["conv1"]["bias"],
                        "SAME")
        x = avg_pool2(jnp.maximum(x, 0).astype(act))
        x = im2col_conv(x, p["conv2"]["kernel"], p["conv2"]["bias"],
                        "VALID")
        x = avg_pool2(jnp.maximum(x, 0).astype(act))
        x = x.reshape(x.shape[0], -1)              # (B, 400)
        if quant_dense_stack:
            for n in ("fc1", "fc2"):
                xq = quantize_act_static(x, p[n]["act_scale"])
                x = fused.quant_dense(
                    xq, p[n]["q"], p[n]["scale"] * p[n]["act_scale"],
                    p[n]["bias"], relu=True, mode=mode)
            xq = quantize_act_static(x, p["logits"]["act_scale"])
            return fused.quant_dense(
                xq, p["logits"]["q"],
                p["logits"]["scale"] * p["logits"]["act_scale"],
                p["logits"]["bias"], relu=False, mode=mode)
        for n in ("fc1", "fc2"):
            x = fused.dense_relu_inference(x, p[n]["kernel"],
                                           p[n]["bias"], dense_mode)
        out = x @ p["logits"]["kernel"] + p["logits"]["bias"]
        return out.astype(jnp.float32)

    return prep, forward


def prepare_inference(model, params, infer_dtype: str,
                      fused_mode: str, *,
                      calib_x=None) -> tuple[Any, Callable]:
    """(prepared_params, forward) for the inference fast path.

    `params` is the training-layout float32 param tree (host or device);
    `infer_dtype` in {bfloat16, int8}; `fused_mode` a RESOLVED
    ops.fused mode (resolve(cfg.fused_kernels, platform)). forward is a
    pure function (prepared, x_u8) -> f32 logits, jit-ready with the
    same signature as the training-precision engine forward. float32 is
    refused by design: that precision serves the training-identical
    reference forward, which is the engine's own default path.
    `calib_x` overrides the activation-calibration batch (uint8 images;
    default: calibration_batch()) on the int8 Pallas routes — other
    routes have no activation quantization and ignore it."""
    from distributedmnist_tpu import models
    from distributedmnist_tpu.ops import fused

    if infer_dtype == "float32":
        raise ValueError(
            "float32 serves the training-identical reference forward — "
            "the inference fast path only exists for lower precisions")
    if infer_dtype not in INFER_DTYPES:
        raise ValueError(
            f"unknown infer dtype {infer_dtype!r} (expected one of "
            f"{INFER_DTYPES})")
    if fused_mode not in (fused.XLA, fused.PALLAS,
                          fused.PALLAS_INTERPRET):
        raise ValueError(
            f"fused_mode must be RESOLVED (ops.fused.resolve), got "
            f"{fused_mode!r}")
    import jax

    params = jax.tree.map(np.asarray, params)
    if infer_dtype == MEGAKERNEL:
        if not isinstance(model, models.MLP):
            raise ValueError(
                f"no megakernel for model {type(model).__name__}: the "
                "whole-net fused forward exists for the MLP only "
                "(MEGAKERNEL_MODELS) — other dtypes still apply")
        return _prepare_mlp_megakernel(params, fused_mode)
    if isinstance(model, models.MLP):
        return _prepare_mlp(params, infer_dtype, fused_mode,
                            calib_x=calib_x)
    if isinstance(model, models.LeNet5):
        return _prepare_lenet(params, infer_dtype, fused_mode,
                              calib_x=calib_x)
    raise ValueError(
        f"no inference fast path for model {type(model).__name__}; "
        "teach serve/quantize.py its layer structure first")
