"""Serving observability: per-request latency percentiles, queue depth,
batch-occupancy histogram, and requests/sec — reported in the repo's
JSON-line record shape (a dict with a "metric" key, serialized by
MetricsLogger.summary_line) so utils/supervise.py acceptors can watch a
serving process exactly the way they watch the bench.

Occupancy is the serving-side analogue of MFU: rows actually served per
bucket slot compiled-and-executed. A low-occupancy bucket histogram says
max_wait_us is too small (batches dispatch before filling) or traffic is
too bursty for the bucket ladder; the latency percentiles say what that
coalescing costs each request.
"""

from __future__ import annotations

import time
from collections import deque

from distributedmnist_tpu.analysis.locks import make_lock
from distributedmnist_tpu.utils import MetricsLogger, percentiles


class ServeMetrics:
    """Thread-safe accumulator; snapshot() is a plain dict, record() the
    JSON-line-ready form. reset() reopens the measurement window (the
    bench resets between sweep points)."""

    def __init__(self, max_latency_samples: int = 100_000):
        self._lock = make_lock("serve.metrics")
        self._max_samples = max_latency_samples
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.monotonic()
            self._lat_s: deque = deque(maxlen=self._max_samples)
            self._requests = 0
            self._rows = 0
            self._batches = 0
            self._rejected_requests = 0
            self._rejected_rows = 0
            self._occupancy: dict[int, list] = {}  # bucket -> [batches,
            self._depth_sum = 0                    #            rows]
            self._depth_max = 0
            # pipeline split (ISSUE 2): host staging time vs blocking
            # device->host fetch time per batch, and the in-flight depth
            # gauge — together they say whether the bounded window is
            # actually overlapping (staging+fetch >> batch period) or
            # idling at depth 1.
            self._staging_s: deque = deque(maxlen=self._max_samples)
            self._fetch_s: deque = deque(maxlen=self._max_samples)
            self._dispatches = 0
            self._inflight_sum = 0
            self._inflight_max = 0
            # batch-former accounting (ISSUE 4): rows the engine
            # actually executed (bucket slots) vs rows a client asked
            # for — their gap is pure padding waste, the quantity the
            # cost-model scheduler exists to shrink — plus the adaptive
            # controller's effective coalescing wait gauge.
            self._dispatched_rows = 0
            self._padded_rows = 0
            self._wait_last_s = None
            self._wait_sum_s = 0.0
            self._wait_n = 0
            # model-lifecycle split (ISSUE 3): per-version populations
            # (canary vs live separability) and shadow-comparison
            # aggregates. Keyed by the version labels the registry
            # assigns; requests served before version plumbing existed
            # (or by a bare engine) simply don't tag.
            self._by_version: dict[str, dict] = {}
            self._shadow: dict[str, dict] = {}   # "live->shadow" pairs
            self._shadow_errors = 0
            self._shadow_dropped = 0
            # resilience accounting (ISSUE 5): the unhappy path must be
            # as observable as the happy one — deadline sheds (504s that
            # never cost device work), bisection activity (splits,
            # isolated culprits, rescued cohort-mates), raw error
            # fan-outs, and breaker trips / auto-rollbacks.
            self._deadline_shed_requests = 0
            self._deadline_shed_rows = 0
            self._bisect_splits = 0
            self._poison_isolated_requests = 0
            self._poison_isolated_rows = 0
            self._bisect_rescued_requests = 0
            self._bisect_rescued_rows = 0
            self._dispatch_error_requests = 0
            self._fetch_error_requests = 0
            self._breaker_trips = 0
            self._rollbacks = 0
            self._last_rollback = None       # {"from", "to", "at"}
            # fleet accounting (ISSUE 6): per-replica batch populations
            # (attribution rides the handle's replica tag, exactly like
            # by_version), plus the failover/hedge counters — how often
            # redundancy, not retry, absorbed a fault.
            self._by_replica: dict[str, dict] = {}
            # per-precision batch populations (ISSUE 7): attribution
            # rides the handle's infer_dtype tag like version/replica —
            # after a dtype promote, the split says which precision
            # actually served the window.
            self._by_dtype: dict[str, dict] = {}
            self._failovers: dict[str, int] = {}   # kind -> count
            self._last_failover = None     # {"kind", "from", "to", "at"}
            self._hedges = 0
            self._hedge_wins = 0
            self._replica_trips: dict[str, int] = {}   # rid -> trips

    # -- recording hooks (called by the batcher) ---------------------------

    def _version_stats(self, version: str) -> dict:
        # caller holds the lock; per-version latency deques are smaller
        # than the global one (populations are a fraction of traffic)
        return self._by_version.setdefault(version, {
            "requests": 0, "rows": 0, "batches": 0,
            "lat": deque(maxlen=min(self._max_samples, 10_000))})

    def record_latency(self, seconds: float, rows: int = 1,
                       version: str = None) -> None:
        with self._lock:
            self._lat_s.append(seconds)
            self._requests += 1
            self._rows += rows
            if version is not None:
                v = self._version_stats(version)
                v["requests"] += 1
                v["rows"] += rows
                v["lat"].append(seconds)

    def record_dispatch(self, staging_seconds: float,
                        inflight: int = 1) -> None:
        """One batch dispatched: host staging time (pad + device_put +
        enqueue, no fetch) and the pipeline depth right after dispatch."""
        with self._lock:
            self._staging_s.append(staging_seconds)
            self._dispatches += 1
            self._inflight_sum += inflight
            self._inflight_max = max(self._inflight_max, inflight)

    def record_fetch(self, seconds: float) -> None:
        """One batch's blocking device->host value fetch completed."""
        with self._lock:
            self._fetch_s.append(seconds)

    def record_batch(self, rows: int, bucket: int,
                     queue_depth: int, version: str = None,
                     replica: str = None, infer_dtype: str = None) -> None:
        with self._lock:
            self._batches += 1
            occ = self._occupancy.setdefault(bucket, [0, 0])
            occ[0] += 1
            occ[1] += rows
            self._dispatched_rows += bucket
            self._padded_rows += max(bucket - rows, 0)
            self._depth_sum += queue_depth
            self._depth_max = max(self._depth_max, queue_depth)
            if version is not None:
                self._version_stats(version)["batches"] += 1
            if replica is not None:
                s = self._by_replica.setdefault(
                    replica, {"batches": 0, "rows": 0})
                s["batches"] += 1
                s["rows"] += rows
            if infer_dtype is not None:
                s = self._by_dtype.setdefault(
                    infer_dtype, {"batches": 0, "rows": 0})
                s["batches"] += 1
                s["rows"] += rows

    def record_wait(self, seconds: float) -> None:
        """The effective coalescing wait the dispatch thread used for
        one drain (the adaptive controller's current operating point,
        == the static max_wait when adaptation is off)."""
        with self._lock:
            self._wait_last_s = seconds
            self._wait_sum_s += seconds
            self._wait_n += 1

    def record_reject(self, rows: int = 1) -> None:
        with self._lock:
            self._rejected_requests += 1
            self._rejected_rows += rows

    def record_shadow(self, live_version: str, shadow_version: str,
                      rows: int, agree_rows: int,
                      max_abs_diff: float) -> None:
        """One shadowed batch compared: how many rows' argmax classes
        agreed between live and candidate, and the worst logit gap."""
        with self._lock:
            s = self._shadow.setdefault(
                f"{live_version}->{shadow_version}",
                {"batches": 0, "rows": 0, "agree_rows": 0,
                 "max_abs_diff": 0.0})
            s["batches"] += 1
            s["rows"] += rows
            s["agree_rows"] += agree_rows
            s["max_abs_diff"] = max(s["max_abs_diff"], max_abs_diff)

    def record_shadow_error(self) -> None:
        """A shadow dispatch/fetch failed (swallowed — live traffic is
        unaffected, but a broken candidate must be visible)."""
        with self._lock:
            self._shadow_errors += 1

    def record_shadow_drop(self) -> None:
        """A sampled batch skipped its shadow duplicate because the
        outstanding-duplication cap was hit (slow/wedged candidate):
        the comparison coverage silently shrinking must be visible."""
        with self._lock:
            self._shadow_dropped += 1

    # -- resilience hooks (ISSUE 5) ----------------------------------------

    def record_deadline_shed(self, rows: int = 1) -> None:
        """One request shed because its client deadline expired before
        dispatch (504-fast; zero device work spent)."""
        with self._lock:
            self._deadline_shed_requests += 1
            self._deadline_shed_rows += rows

    def record_bisect_split(self) -> None:
        """One failed segment split into halves for retry."""
        with self._lock:
            self._bisect_splits += 1

    def record_poison_isolated(self, rows: int = 1) -> None:
        """One culprit request isolated down to its singleton dispatch
        and failed alone (its cohort-mates were rescued)."""
        with self._lock:
            self._poison_isolated_requests += 1
            self._poison_isolated_rows += rows

    def record_bisect_rescued(self, requests: int, rows: int) -> None:
        """One sub-segment of a bisected batch dispatched clean: these
        requests would have failed with their cohort pre-ISSUE 5."""
        with self._lock:
            self._bisect_rescued_requests += requests
            self._bisect_rescued_rows += rows

    def record_dispatch_error(self, requests: int) -> None:
        """A whole segment failed at dispatch WITHOUT isolation (no
        resilience policy, or bisection disabled)."""
        with self._lock:
            self._dispatch_error_requests += requests

    def record_fetch_error(self, requests: int) -> None:
        """A dispatched batch's fetch failed; its cohort fanned out the
        error (the circuit breaker's raw signal)."""
        with self._lock:
            self._fetch_error_requests += requests

    def record_breaker_trip(self, version: str) -> None:
        with self._lock:
            self._breaker_trips += 1

    def record_rollback(self, from_version: str, to_version: str) -> None:
        """The breaker's trip demoted `from_version` and auto-promoted
        `to_version` (the newest healthy registry resident)."""
        with self._lock:
            self._rollbacks += 1
            self._last_rollback = {"from": from_version,
                                   "to": to_version,
                                   # lint: allow[DML004] wall-clock event stamp for operators
                                   "at": round(time.time(), 3)}

    # -- fleet hooks (ISSUE 6) ---------------------------------------------

    def record_failover(self, kind: str, from_replica: str,
                        to_replica: str) -> None:
        """One batch rescued on a sibling after its replica died at
        `kind` ('dispatch' | 'fetch') — the fault cost latency, not an
        error."""
        with self._lock:
            self._failovers[kind] = self._failovers.get(kind, 0) + 1
            self._last_failover = {"kind": kind, "from": from_replica,
                                   "to": to_replica,
                                   # lint: allow[DML004] wall-clock event stamp for operators
                                   "at": round(time.time(), 3)}

    def record_hedge(self, win: bool) -> None:
        """One hedged fetch resolved: win=True means the duplicate beat
        the overdue primary (the hedge bought the tail back)."""
        with self._lock:
            self._hedges += 1
            if win:
                self._hedge_wins += 1

    def record_replica_trip(self, replica: str) -> None:
        """A replica's breaker tripped: it is excluded from dispatch
        for its cooldown while siblings absorb its share. Keyed by
        replica — after an incident, WHICH replica kept tripping is
        the question."""
        with self._lock:
            self._replica_trips[replica] = (
                self._replica_trips.get(replica, 0) + 1)

    # -- reporting ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            lat_ms = {k: (round(v * 1e3, 3) if v is not None else None)
                      for k, v in percentiles(list(self._lat_s)).items()}
            occupancy = {
                str(b): {"batches": n, "rows": rows,
                         "occupancy": round(rows / (n * b), 4)}
                for b, (n, rows) in sorted(self._occupancy.items())}
            return {
                "window_s": round(elapsed, 3),
                "requests": self._requests,
                "rows": self._rows,
                "batches": self._batches,
                "requests_per_sec": round(self._requests / elapsed, 2),
                "rows_per_sec": round(self._rows / elapsed, 2),
                "latency_ms": lat_ms,
                "batch_occupancy": occupancy,
                # The scheduler's report card: executed bucket slots vs
                # real rows (their ratio is the FLOP fraction burned on
                # padding), the per-bucket dispatch histogram, and the
                # effective-wait operating point.
                "dispatched_rows": self._dispatched_rows,
                "padded_rows": self._padded_rows,
                "padding_waste_ratio": (
                    round(self._padded_rows / self._dispatched_rows, 4)
                    if self._dispatched_rows else None),
                "bucket_dispatches": {
                    str(b): n
                    for b, (n, _) in sorted(self._occupancy.items())},
                "effective_wait_us": {
                    "last": (round(self._wait_last_s * 1e6, 1)
                             if self._wait_n else None),
                    "mean": (round(self._wait_sum_s / self._wait_n * 1e6,
                                   1)
                             if self._wait_n else None),
                },
                "mean_rows_per_batch": (
                    round(self._rows / self._batches, 2)
                    if self._batches else None),
                "queue_depth_mean": (
                    round(self._depth_sum / self._batches, 2)
                    if self._batches else None),
                "queue_depth_max": self._depth_max,
                "rejected_requests": self._rejected_requests,
                "rejected_rows": self._rejected_rows,
                "staging_ms": {
                    k: (round(v * 1e3, 3) if v is not None else None)
                    for k, v in percentiles(
                        list(self._staging_s)).items()},
                "fetch_ms": {
                    k: (round(v * 1e3, 3) if v is not None else None)
                    for k, v in percentiles(list(self._fetch_s)).items()},
                "inflight_mean": (
                    round(self._inflight_sum / self._dispatches, 2)
                    if self._dispatches else None),
                "inflight_max": self._inflight_max,
                "by_version": {
                    v: {"requests": s["requests"], "rows": s["rows"],
                        "batches": s["batches"],
                        "latency_ms": {
                            k: (round(x * 1e3, 3) if x is not None
                                else None)
                            for k, x in percentiles(
                                list(s["lat"])).items()}}
                    for v, s in sorted(self._by_version.items())},
                "shadow": {
                    pair: {**s,
                           "agreement": (round(s["agree_rows"]
                                               / s["rows"], 4)
                                         if s["rows"] else None),
                           "max_abs_diff": round(s["max_abs_diff"], 6)}
                    for pair, s in sorted(self._shadow.items())},
                "shadow_errors": self._shadow_errors,
                "shadow_dropped": self._shadow_dropped,
                "by_replica": {r: dict(s) for r, s in
                               sorted(self._by_replica.items())},
                "by_dtype": {d: dict(s) for d, s in
                             sorted(self._by_dtype.items())},
                "fleet": {
                    "failovers": dict(self._failovers),
                    "failovers_total": sum(self._failovers.values()),
                    "last_failover": self._last_failover,
                    "hedges": self._hedges,
                    "hedge_wins": self._hedge_wins,
                    "replica_trips": sum(self._replica_trips.values()),
                    "replica_trips_by_replica": dict(self._replica_trips),
                },
                "resilience": {
                    "deadline_shed_requests": self._deadline_shed_requests,
                    "deadline_shed_rows": self._deadline_shed_rows,
                    "bisect_splits": self._bisect_splits,
                    "poison_isolated_requests":
                        self._poison_isolated_requests,
                    "poison_isolated_rows": self._poison_isolated_rows,
                    "bisect_rescued_requests":
                        self._bisect_rescued_requests,
                    "bisect_rescued_rows": self._bisect_rescued_rows,
                    "dispatch_error_requests":
                        self._dispatch_error_requests,
                    "fetch_error_requests": self._fetch_error_requests,
                    "breaker_trips": self._breaker_trips,
                    "rollbacks": self._rollbacks,
                    "last_rollback": self._last_rollback,
                },
            }

    def record(self) -> dict:
        """The supervise-acceptable heartbeat record: a JSON-able dict
        with the conventional 'metric' key."""
        return {"metric": "serve_stats", **self.snapshot()}

    def heartbeat_line(self) -> str:
        return MetricsLogger.summary_line(self.record())
