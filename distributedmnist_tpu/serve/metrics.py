"""Serving observability: per-request latency percentiles, queue depth,
batch-occupancy histogram, and requests/sec — reported in the repo's
JSON-line record shape (a dict with a "metric" key, serialized by
MetricsLogger.summary_line) so utils/supervise.py acceptors can watch a
serving process exactly the way they watch the bench.

Occupancy is the serving-side analogue of MFU: rows actually served per
bucket slot compiled-and-executed. A low-occupancy bucket histogram says
max_wait_us is too small (batches dispatch before filling) or traffic is
too bursty for the bucket ladder; the latency percentiles say what that
coalescing costs each request.
"""

from __future__ import annotations

import time
from collections import deque

from distributedmnist_tpu.analysis.locks import make_lock
from distributedmnist_tpu.utils import MetricsLogger, percentiles


class ServeMetrics:
    """Thread-safe accumulator; snapshot() is a plain dict, record() the
    JSON-line-ready form. reset() reopens the measurement window (the
    bench resets between sweep points)."""

    def __init__(self, max_latency_samples: int = 100_000):
        self._lock = make_lock("serve.metrics")
        self._max_samples = max_latency_samples
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.monotonic()
            self._lat_s: deque = deque(maxlen=self._max_samples)
            self._requests = 0
            self._rows = 0
            self._batches = 0
            self._rejected_requests = 0
            self._rejected_rows = 0
            self._occupancy: dict[int, list] = {}  # bucket -> [batches,
            self._depth_sum = 0                    #            rows]
            self._depth_max = 0
            # pipeline split (ISSUE 2): host staging time vs blocking
            # device->host fetch time per batch, and the in-flight depth
            # gauge — together they say whether the bounded window is
            # actually overlapping (staging+fetch >> batch period) or
            # idling at depth 1.
            self._staging_s: deque = deque(maxlen=self._max_samples)
            self._fetch_s: deque = deque(maxlen=self._max_samples)
            self._dispatches = 0
            self._inflight_sum = 0
            self._inflight_max = 0
            # batch-former accounting (ISSUE 4): rows the engine
            # actually executed (bucket slots) vs rows a client asked
            # for — their gap is pure padding waste, the quantity the
            # cost-model scheduler exists to shrink — plus the adaptive
            # controller's effective coalescing wait gauge.
            self._dispatched_rows = 0
            self._padded_rows = 0
            self._wait_last_s = None
            self._wait_sum_s = 0.0
            self._wait_n = 0
            # model-lifecycle split (ISSUE 3): per-version populations
            # (canary vs live separability) and shadow-comparison
            # aggregates. Keyed by the version labels the registry
            # assigns; requests served before version plumbing existed
            # (or by a bare engine) simply don't tag.
            self._by_version: dict[str, dict] = {}
            self._shadow: dict[str, dict] = {}   # "live->shadow" pairs
            self._shadow_errors = 0
            self._shadow_dropped = 0
            # resilience accounting (ISSUE 5): the unhappy path must be
            # as observable as the happy one — deadline sheds (504s that
            # never cost device work), bisection activity (splits,
            # isolated culprits, rescued cohort-mates), raw error
            # fan-outs, and breaker trips / auto-rollbacks.
            self._deadline_shed_requests = 0
            self._deadline_shed_rows = 0
            self._bisect_splits = 0
            self._poison_isolated_requests = 0
            self._poison_isolated_rows = 0
            self._bisect_rescued_requests = 0
            self._bisect_rescued_rows = 0
            self._dispatch_error_requests = 0
            self._fetch_error_requests = 0
            self._breaker_trips = 0
            self._breaker_trips_by_version: dict[str, int] = {}
            self._rollbacks = 0
            self._last_rollback = None       # {"from", "to", "at"}
            # fleet accounting (ISSUE 6): per-replica batch populations
            # (attribution rides the handle's replica tag, exactly like
            # by_version), plus the failover/hedge counters — how often
            # redundancy, not retry, absorbed a fault.
            self._by_replica: dict[str, dict] = {}
            # per-precision batch populations (ISSUE 7): attribution
            # rides the handle's infer_dtype tag like version/replica —
            # after a dtype promote, the split says which precision
            # actually served the window.
            self._by_dtype: dict[str, dict] = {}
            self._failovers: dict[str, int] = {}   # kind -> count
            self._last_failover = None     # {"kind", "from", "to", "at"}
            self._hedges = 0
            self._hedge_wins = 0
            self._replica_trips: dict[str, int] = {}   # rid -> trips
            # prediction-cache front layer (ISSUE 10): requests served
            # without touching the pipeline — straight cache hits and
            # single-flight collapsed followers — plus the batcher's
            # intra-batch dedup riders. The cache's own hit/miss/evict
            # counters live in PredictionCache.stats(); these are the
            # SERVED-population side (they also feed the global
            # request/latency/by_version/by_dtype accounting, so a
            # cache hit never silently skips observability).
            self._cache_hit_requests = 0
            self._cache_hit_rows = 0
            self._cache_collapsed_requests = 0
            self._dedup_requests = 0
            self._dedup_rows = 0
            # fast-lane accounting (ISSUE 14): requests that bypassed
            # the coalescing path entirely — dispatched on the caller's
            # thread through the lane decision. They feed every global
            # population above too (the lane skips queueing, never
            # observability); these counters are the LANE split, so an
            # operator can read what fraction of traffic ran bypass vs
            # coalesced at a glance.
            self._fastpath_dispatches = 0
            self._fastpath_rows = 0
            # cascade accounting (ISSUE 17): per-class request counts
            # (the X-Accuracy-Class split), per-stage dispatched rows
            # (cheap dtype vs the f32 escalation stage), escalation
            # volume, and requests that degraded to the plain live
            # route because no calibrated cascade existed.
            self._cascade_class = {}
            self._cascade_stage_rows = {}
            self._cascade_escalated_requests = 0
            self._cascade_escalated_rows = 0
            self._cascade_degraded = 0
            # tenancy accounting (ISSUE 18): per-tenant admission /
            # shed / dispatch / SLO populations and per-model demand,
            # recorded by serve/tenancy.py's GlobalScheduler. Keyed by
            # the RESOLVED SLO-class name (unknown X-Tenant headers
            # collapse into "default" at admission), so cardinality is
            # bounded by configuration, never by client-chosen labels.
            self._by_tenant: dict[str, dict] = {}
            self._by_model: dict[str, dict] = {}
            # autoscale accounting (ISSUE 20): the control loop's
            # current scale (units on the actuator's disclosed cost
            # basis), applied decisions by direction, decisions the
            # cooldown suppressed, ceiling-hit ticks (disclosed
            # saturation), and the last applied action's priced cost.
            self._autoscale_scale: int = 0
            self._autoscale_decisions: dict[str, int] = {}
            self._autoscale_suppressed = 0
            self._autoscale_saturated = 0
            self._autoscale_last_cost: float = 0.0

    # -- recording hooks (called by the batcher) ---------------------------

    def _version_stats(self, version: str) -> dict:
        # caller holds the lock; per-version latency deques are smaller
        # than the global one (populations are a fraction of traffic)
        return self._by_version.setdefault(version, {
            "requests": 0, "rows": 0, "batches": 0,
            "lat": deque(maxlen=min(self._max_samples, 10_000))})

    def record_latency(self, seconds: float, rows: int = 1,
                       version: str = None) -> None:
        with self._lock:
            self._lat_s.append(seconds)
            self._requests += 1
            self._rows += rows
            if version is not None:
                v = self._version_stats(version)
                v["requests"] += 1
                v["rows"] += rows
                v["lat"].append(seconds)

    def record_cache_hit(self, seconds: float, rows: int = 1,
                         version: str = None, infer_dtype: str = None,
                         collapsed: bool = False) -> None:
        """One request served by the prediction-cache front layer
        (ISSUE 10) — a straight hit (collapsed=False) or a
        single-flight follower resolved from its leader's bytes
        (collapsed=True). Records the SAME populations a computed
        response gets (global request/row/latency, per-version,
        per-dtype): the front layer must never make served traffic
        invisible."""
        with self._lock:
            self._lat_s.append(seconds)
            self._requests += 1
            self._rows += rows
            if collapsed:
                self._cache_collapsed_requests += 1
            else:
                self._cache_hit_requests += 1
            self._cache_hit_rows += rows
            if version is not None:
                v = self._version_stats(version)
                v["requests"] += 1
                v["rows"] += rows
                v["lat"].append(seconds)
            if infer_dtype is not None:
                s = self._by_dtype.setdefault(
                    infer_dtype, {"batches": 0, "rows": 0})
                s["rows"] += rows

    def record_fastpath(self, rows: int = 1) -> None:
        """One request served through the single-request bypass lane
        (ISSUE 14): dispatched on the caller's thread, no coalesce
        wait, no queue hand-offs. Latency/batch/version populations
        are recorded by the same hooks a coalesced request uses; this
        is the lane-attribution counter."""
        with self._lock:
            self._fastpath_dispatches += 1
            self._fastpath_rows += rows

    def record_cascade_class(self, accuracy_class: str) -> None:
        """One request entered the cascade front under this accuracy
        class (fast / balanced / exact) — counted at submit, before
        degrade/shed decisions, so the class split reflects demand."""
        with self._lock:
            self._cascade_class[accuracy_class] = \
                self._cascade_class.get(accuracy_class, 0) + 1

    def record_cascade_stage(self, stage: str, rows: int) -> None:
        """Rows a cascade stage answered: the cheap dtype counts every
        stage-1 row (all of a balanced request's), 'float32' only the
        escalated slice — their ratio is the measured goodput story."""
        with self._lock:
            s = self._cascade_stage_rows.setdefault(
                stage, {"rows": 0, "dispatches": 0})
            s["rows"] += rows
            s["dispatches"] += 1

    def record_cascade_escalation(self, rows: int) -> None:
        """One balanced request had `rows` rows under the calibrated
        margin threshold and re-submitted them to f32."""
        with self._lock:
            self._cascade_escalated_requests += 1
            self._cascade_escalated_rows += rows

    def record_cascade_degraded(self) -> None:
        """A cascade-front request served by the plain live route: no
        calibrated cascade on the live version (warming, or a promote
        to an uncascaded version). Loud, never an error."""
        with self._lock:
            self._cascade_degraded += 1

    # -- tenancy hooks (ISSUE 18, called by the GlobalScheduler) -----------

    def _tenant_stats(self, tenant: str) -> dict:
        # caller holds the lock, like _version_stats
        return self._by_tenant.setdefault(tenant, {
            "requests": 0, "rows": 0, "dispatched_rows": 0,
            "quota_sheds": 0, "watermark_sheds": 0, "deadline_sheds": 0,
            "shed_rows": 0, "cache_hits": 0, "slo_hits": 0,
            "slo_total": 0,
            "lat": deque(maxlen=min(self._max_samples, 10_000))})

    def record_tenant_request(self, tenant: str, model: str,
                              rows: int = 1) -> None:
        """One request ADMITTED (or cache-served) for a tenant, routed
        to a model — the demand side of the by_tenant/by_model split."""
        with self._lock:
            t = self._tenant_stats(tenant)
            t["requests"] += 1
            t["rows"] += rows
            m = self._by_model.setdefault(
                model, {"requests": 0, "rows": 0, "dispatched_rows": 0})
            m["requests"] += 1
            m["rows"] += rows

    def record_tenant_shed(self, tenant: str, kind: str,
                           rows: int = 1) -> None:
        """One tenant request shed at admission or grant time:
        kind in {"quota" (429), "watermark" (503), "deadline" (504)}.
        The global reject/deadline counters are recorded separately by
        the scheduler — this is the per-tenant attribution."""
        with self._lock:
            t = self._tenant_stats(tenant)
            t[f"{kind}_sheds"] += 1
            t["shed_rows"] += rows

    def record_tenant_dispatch(self, tenant: str, model: str,
                               rows: int) -> None:
        """Rows GRANTED to a tenant by one WFQ dispatch decision — the
        service side, whose share over all tenants is the fairness
        ratio's numerator."""
        with self._lock:
            self._tenant_stats(tenant)["dispatched_rows"] += rows
            m = self._by_model.setdefault(
                model, {"requests": 0, "rows": 0, "dispatched_rows": 0})
            m["dispatched_rows"] += rows

    def record_tenant_cache_hit(self, tenant: str,
                                rows: int = 1) -> None:
        """A would-be quota/watermark shed served from the prediction
        cache instead (the cache-aware shed): zero device work, never
        a 429/503."""
        with self._lock:
            self._tenant_stats(tenant)["cache_hits"] += 1

    def record_tenant_done(self, tenant: str, seconds: float,
                           slo_ok=None) -> None:
        """One tenant request completed end-to-end (admission to
        resolution). `slo_ok` says whether it made its deadline (None
        = best-effort class, excluded from attainment)."""
        with self._lock:
            t = self._tenant_stats(tenant)
            t["lat"].append(seconds)
            if slo_ok is not None:
                t["slo_total"] += 1
                if slo_ok:
                    t["slo_hits"] += 1

    def record_dedup(self, requests: int, rows: int) -> None:
        """Intra-batch dedup riders (ISSUE 10): identical rows inside
        one coalesced drain that dispatched once and fanned out —
        `rows` is the device work the riders did NOT cost."""
        with self._lock:
            self._dedup_requests += requests
            self._dedup_rows += rows

    def record_dispatch(self, staging_seconds: float,
                        inflight: int = 1) -> None:
        """One batch dispatched: host staging time (pad + device_put +
        enqueue, no fetch) and the pipeline depth right after dispatch."""
        with self._lock:
            self._staging_s.append(staging_seconds)
            self._dispatches += 1
            self._inflight_sum += inflight
            self._inflight_max = max(self._inflight_max, inflight)

    def record_fetch(self, seconds: float) -> None:
        """One batch's blocking device->host value fetch completed."""
        with self._lock:
            self._fetch_s.append(seconds)

    def record_batch(self, rows: int, bucket: int,
                     queue_depth: int, version: str = None,
                     replica: str = None, infer_dtype: str = None) -> None:
        with self._lock:
            self._batches += 1
            occ = self._occupancy.setdefault(bucket, [0, 0])
            occ[0] += 1
            occ[1] += rows
            self._dispatched_rows += bucket
            self._padded_rows += max(bucket - rows, 0)
            self._depth_sum += queue_depth
            self._depth_max = max(self._depth_max, queue_depth)
            if version is not None:
                self._version_stats(version)["batches"] += 1
            if replica is not None:
                s = self._by_replica.setdefault(
                    replica, {"batches": 0, "rows": 0})
                s["batches"] += 1
                s["rows"] += rows
            if infer_dtype is not None:
                s = self._by_dtype.setdefault(
                    infer_dtype, {"batches": 0, "rows": 0})
                s["batches"] += 1
                s["rows"] += rows

    def record_wait(self, seconds: float) -> None:
        """The effective coalescing wait the dispatch thread used for
        one drain (the adaptive controller's current operating point,
        == the static max_wait when adaptation is off)."""
        with self._lock:
            self._wait_last_s = seconds
            self._wait_sum_s += seconds
            self._wait_n += 1

    def record_reject(self, rows: int = 1) -> None:
        with self._lock:
            self._rejected_requests += 1
            self._rejected_rows += rows

    def rejected_total(self) -> int:
        """Cheap counter read for the autoscaler's shed signal (ISSUE
        20) — snapshot() does percentile math, far too heavy for a
        sub-second control tick."""
        with self._lock:
            return self._rejected_requests

    def record_shadow(self, live_version: str, shadow_version: str,
                      rows: int, agree_rows: int,
                      max_abs_diff: float) -> None:
        """One shadowed batch compared: how many rows' argmax classes
        agreed between live and candidate, and the worst logit gap."""
        with self._lock:
            s = self._shadow.setdefault(
                f"{live_version}->{shadow_version}",
                {"batches": 0, "rows": 0, "agree_rows": 0,
                 "max_abs_diff": 0.0})
            s["batches"] += 1
            s["rows"] += rows
            s["agree_rows"] += agree_rows
            s["max_abs_diff"] = max(s["max_abs_diff"], max_abs_diff)

    def record_shadow_error(self) -> None:
        """A shadow dispatch/fetch failed (swallowed — live traffic is
        unaffected, but a broken candidate must be visible)."""
        with self._lock:
            self._shadow_errors += 1

    def record_shadow_drop(self) -> None:
        """A sampled batch skipped its shadow duplicate because the
        outstanding-duplication cap was hit (slow/wedged candidate):
        the comparison coverage silently shrinking must be visible."""
        with self._lock:
            self._shadow_dropped += 1

    # -- resilience hooks (ISSUE 5) ----------------------------------------

    def record_deadline_shed(self, rows: int = 1) -> None:
        """One request shed because its client deadline expired before
        dispatch (504-fast; zero device work spent)."""
        with self._lock:
            self._deadline_shed_requests += 1
            self._deadline_shed_rows += rows

    def record_bisect_split(self) -> None:
        """One failed segment split into halves for retry."""
        with self._lock:
            self._bisect_splits += 1

    def record_poison_isolated(self, rows: int = 1) -> None:
        """One culprit request isolated down to its singleton dispatch
        and failed alone (its cohort-mates were rescued)."""
        with self._lock:
            self._poison_isolated_requests += 1
            self._poison_isolated_rows += rows

    def record_bisect_rescued(self, requests: int, rows: int) -> None:
        """One sub-segment of a bisected batch dispatched clean: these
        requests would have failed with their cohort pre-ISSUE 5."""
        with self._lock:
            self._bisect_rescued_requests += requests
            self._bisect_rescued_rows += rows

    def record_dispatch_error(self, requests: int) -> None:
        """A whole segment failed at dispatch WITHOUT isolation (no
        resilience policy, or bisection disabled)."""
        with self._lock:
            self._dispatch_error_requests += requests

    def record_fetch_error(self, requests: int) -> None:
        """A dispatched batch's fetch failed; its cohort fanned out the
        error (the circuit breaker's raw signal)."""
        with self._lock:
            self._fetch_error_requests += requests

    def record_breaker_trip(self, version: str) -> None:
        """One circuit-breaker trip, attributed to the version whose
        failure window crossed the ratio (the argument used to be
        silently dropped — ISSUE 9 satellite): after an incident,
        WHICH version kept tripping is the question, exactly as it is
        for replicas (`_replica_trips`)."""
        with self._lock:
            self._breaker_trips += 1
            if version is not None:
                self._breaker_trips_by_version[version] = (
                    self._breaker_trips_by_version.get(version, 0) + 1)

    def record_rollback(self, from_version: str, to_version: str) -> None:
        """The breaker's trip demoted `from_version` and auto-promoted
        `to_version` (the newest healthy registry resident)."""
        with self._lock:
            self._rollbacks += 1
            self._last_rollback = {"from": from_version,
                                   "to": to_version,
                                   # lint: allow[DML004] wall-clock event stamp for operators
                                   "at": round(time.time(), 3)}

    # -- fleet hooks (ISSUE 6) ---------------------------------------------

    def record_failover(self, kind: str, from_replica: str,
                        to_replica: str) -> None:
        """One batch rescued on a sibling after its replica died at
        `kind` ('dispatch' | 'fetch') — the fault cost latency, not an
        error."""
        with self._lock:
            self._failovers[kind] = self._failovers.get(kind, 0) + 1
            self._last_failover = {"kind": kind, "from": from_replica,
                                   "to": to_replica,
                                   # lint: allow[DML004] wall-clock event stamp for operators
                                   "at": round(time.time(), 3)}

    def record_hedge(self, win: bool) -> None:
        """One hedged fetch resolved: win=True means the duplicate beat
        the overdue primary (the hedge bought the tail back)."""
        with self._lock:
            self._hedges += 1
            if win:
                self._hedge_wins += 1

    def record_replica_trip(self, replica: str) -> None:
        """A replica's breaker tripped: it is excluded from dispatch
        for its cooldown while siblings absorb its share. Keyed by
        replica — after an incident, WHICH replica kept tripping is
        the question."""
        with self._lock:
            self._replica_trips[replica] = (
                self._replica_trips.get(replica, 0) + 1)

    def record_autoscale_scale(self, units: int) -> None:
        """The autoscaler announced its starting scale (units on the
        actuator's cost basis — window slots or workers)."""
        with self._lock:
            self._autoscale_scale = units

    def record_autoscale_action(self, direction: str, units: int,
                                price_chip_s: float) -> None:
        """One APPLIED scale action: direction (grow|shrink), the
        achieved scale, and the step's cost-model price in
        chip-seconds per second of reserved capacity."""
        with self._lock:
            self._autoscale_scale = units
            self._autoscale_decisions[direction] = (
                self._autoscale_decisions.get(direction, 0) + 1)
            self._autoscale_last_cost = price_chip_s

    def record_autoscale_suppressed(self) -> None:
        """A decision the cooldown window suppressed — the flap
        counter's complement (suppressions are WHY flaps stay zero)."""
        with self._lock:
            self._autoscale_suppressed += 1

    def record_autoscale_saturated(self) -> None:
        """A tick that wanted to grow past the hard ceiling: disclosed
        saturation — the operator's signal to raise provisioning, and
        the bench's ceiling-hit failure-mode row."""
        with self._lock:
            self._autoscale_saturated += 1

    # -- reporting ---------------------------------------------------------

    def snapshot(self) -> dict:
        # Copy raw state under the lock; compute percentiles AFTER
        # releasing it (ISSUE 9 satellite). np.quantile over an
        # up-to-100k-sample deque costs milliseconds — holding the
        # metrics lock through it stalled every recording hook on the
        # dispatch/completion hot path whenever /metrics was polled.
        # The deque copies are O(n) pointer copies (cheap); the math
        # runs on thread-private lists.
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            lat = list(self._lat_s)
            staging = list(self._staging_s)
            fetch = list(self._fetch_s)
            occupancy_raw = {b: (n, rows) for b, (n, rows)
                             in self._occupancy.items()}
            by_version_raw = {
                v: {"requests": s["requests"], "rows": s["rows"],
                    "batches": s["batches"], "lat": list(s["lat"])}
                for v, s in self._by_version.items()}
            shadow_raw = {pair: dict(s)
                          for pair, s in self._shadow.items()}
            by_tenant_raw = {
                t: {**{k: v for k, v in s.items() if k != "lat"},
                    "lat": list(s["lat"])}
                for t, s in self._by_tenant.items()}
            c = {
                "requests": self._requests,
                "rows": self._rows,
                "batches": self._batches,
                "dispatched_rows": self._dispatched_rows,
                "padded_rows": self._padded_rows,
                "wait_last_s": self._wait_last_s,
                "wait_sum_s": self._wait_sum_s,
                "wait_n": self._wait_n,
                "depth_sum": self._depth_sum,
                "depth_max": self._depth_max,
                "rejected_requests": self._rejected_requests,
                "rejected_rows": self._rejected_rows,
                "inflight_sum": self._inflight_sum,
                "inflight_max": self._inflight_max,
                "dispatches": self._dispatches,
                "shadow_errors": self._shadow_errors,
                "shadow_dropped": self._shadow_dropped,
                "by_replica": {r: dict(s)
                               for r, s in self._by_replica.items()},
                "by_dtype": {d: dict(s)
                             for d, s in self._by_dtype.items()},
                "failovers": dict(self._failovers),
                "last_failover": self._last_failover,
                "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "replica_trips": dict(self._replica_trips),
                "cache_hit_requests": self._cache_hit_requests,
                "cache_hit_rows": self._cache_hit_rows,
                "cache_collapsed_requests":
                    self._cache_collapsed_requests,
                "dedup_requests": self._dedup_requests,
                "dedup_rows": self._dedup_rows,
                "fastpath_dispatches": self._fastpath_dispatches,
                "fastpath_rows": self._fastpath_rows,
                "cascade_class": dict(self._cascade_class),
                "cascade_stage_rows": {
                    st: dict(s)
                    for st, s in self._cascade_stage_rows.items()},
                "cascade_escalated_requests":
                    self._cascade_escalated_requests,
                "cascade_escalated_rows": self._cascade_escalated_rows,
                "cascade_degraded": self._cascade_degraded,
                "deadline_shed_requests": self._deadline_shed_requests,
                "deadline_shed_rows": self._deadline_shed_rows,
                "bisect_splits": self._bisect_splits,
                "poison_isolated_requests":
                    self._poison_isolated_requests,
                "poison_isolated_rows": self._poison_isolated_rows,
                "bisect_rescued_requests": self._bisect_rescued_requests,
                "bisect_rescued_rows": self._bisect_rescued_rows,
                "dispatch_error_requests": self._dispatch_error_requests,
                "fetch_error_requests": self._fetch_error_requests,
                "breaker_trips": self._breaker_trips,
                "breaker_trips_by_version":
                    dict(self._breaker_trips_by_version),
                "rollbacks": self._rollbacks,
                "last_rollback": self._last_rollback,
                "by_model": {m: dict(s)
                             for m, s in self._by_model.items()},
                "autoscale_scale": self._autoscale_scale,
                "autoscale_decisions": dict(self._autoscale_decisions),
                "autoscale_suppressed": self._autoscale_suppressed,
                "autoscale_saturated": self._autoscale_saturated,
                "autoscale_last_cost": self._autoscale_last_cost,
            }
        lat_ms = {k: (round(v * 1e3, 3) if v is not None else None)
                  for k, v in percentiles(lat).items()}
        total_tenant_dispatched = sum(
            s["dispatched_rows"] for s in by_tenant_raw.values())
        # escalated rows over cheap-stage rows: the fraction of stage-1
        # work the calibrated threshold sent on to f32 (None before any
        # cascade traffic)
        cheap_stage_rows = sum(
            s["rows"] for st, s in c["cascade_stage_rows"].items()
            if st != "float32")
        cascade_escalation_fraction = (
            round(c["cascade_escalated_rows"] / cheap_stage_rows, 4)
            if cheap_stage_rows else None)
        occupancy = {
            str(b): {"batches": n, "rows": rows,
                     "occupancy": round(rows / (n * b), 4)}
            for b, (n, rows) in sorted(occupancy_raw.items())}
        return {
            "window_s": round(elapsed, 3),
            "requests": c["requests"],
            "rows": c["rows"],
            "batches": c["batches"],
            "requests_per_sec": round(c["requests"] / elapsed, 2),
            "rows_per_sec": round(c["rows"] / elapsed, 2),
            "latency_ms": lat_ms,
            "batch_occupancy": occupancy,
            # The scheduler's report card: executed bucket slots vs
            # real rows (their ratio is the FLOP fraction burned on
            # padding), the per-bucket dispatch histogram, and the
            # effective-wait operating point.
            "dispatched_rows": c["dispatched_rows"],
            "padded_rows": c["padded_rows"],
            "padding_waste_ratio": (
                round(c["padded_rows"] / c["dispatched_rows"], 4)
                if c["dispatched_rows"] else None),
            "bucket_dispatches": {
                str(b): n
                for b, (n, _) in sorted(occupancy_raw.items())},
            "effective_wait_us": {
                "last": (round(c["wait_last_s"] * 1e6, 1)
                         if c["wait_n"] else None),
                "mean": (round(c["wait_sum_s"] / c["wait_n"] * 1e6, 1)
                         if c["wait_n"] else None),
            },
            "mean_rows_per_batch": (
                round(c["rows"] / c["batches"], 2)
                if c["batches"] else None),
            "queue_depth_mean": (
                round(c["depth_sum"] / c["batches"], 2)
                if c["batches"] else None),
            "queue_depth_max": c["depth_max"],
            "rejected_requests": c["rejected_requests"],
            "rejected_rows": c["rejected_rows"],
            "staging_ms": {
                k: (round(v * 1e3, 3) if v is not None else None)
                for k, v in percentiles(staging).items()},
            "fetch_ms": {
                k: (round(v * 1e3, 3) if v is not None else None)
                for k, v in percentiles(fetch).items()},
            "inflight_mean": (
                round(c["inflight_sum"] / c["dispatches"], 2)
                if c["dispatches"] else None),
            "inflight_max": c["inflight_max"],
            "by_version": {
                v: {"requests": s["requests"], "rows": s["rows"],
                    "batches": s["batches"],
                    "latency_ms": {
                        k: (round(x * 1e3, 3) if x is not None
                            else None)
                        for k, x in percentiles(s["lat"]).items()}}
                for v, s in sorted(by_version_raw.items())},
            "shadow": {
                pair: {**s,
                       "agreement": (round(s["agree_rows"]
                                           / s["rows"], 4)
                                     if s["rows"] else None),
                       "max_abs_diff": round(s["max_abs_diff"], 6)}
                for pair, s in sorted(shadow_raw.items())},
            "shadow_errors": c["shadow_errors"],
            "shadow_dropped": c["shadow_dropped"],
            "by_replica": {r: s for r, s in
                           sorted(c["by_replica"].items())},
            "by_dtype": {d: s for d, s in
                         sorted(c["by_dtype"].items())},
            # the tenancy split (ISSUE 18): per-tenant demand, sheds
            # by kind, dispatched service (whose share over all
            # tenants is the WFQ fairness ratio's numerator), SLO
            # attainment, and per-model demand across the catalog
            "by_tenant": {
                t: {**{k: v for k, v in s.items() if k != "lat"},
                    "dispatch_share": (
                        round(s["dispatched_rows"]
                              / total_tenant_dispatched, 4)
                        if total_tenant_dispatched else None),
                    "slo_attainment": (
                        round(s["slo_hits"] / s["slo_total"], 4)
                        if s["slo_total"] else None),
                    "latency_ms": {
                        k: (round(x * 1e3, 3) if x is not None
                            else None)
                        for k, x in percentiles(s["lat"]).items()}}
                for t, s in sorted(by_tenant_raw.items())},
            "by_model": {m: s for m, s in
                         sorted(c["by_model"].items())},
            # the front layer's served populations (ISSUE 10): the
            # cache's own hit/miss/evict counters + hit ratio live in
            # PredictionCache.stats(), surfaced as /metrics' `cache`
            # block by serve.py — this is the request-accounting side
            "cache_served": {
                "hit_requests": c["cache_hit_requests"],
                "hit_rows": c["cache_hit_rows"],
                "collapsed_requests": c["cache_collapsed_requests"],
            },
            "dedup": {
                "requests": c["dedup_requests"],
                "rows": c["dedup_rows"],
            },
            # the lane split (ISSUE 14): bypass-lane requests vs the
            # whole served population — lane_fraction near 1 at low
            # load and near 0 under sustained traffic is the designed
            # shape (the lane closes the moment contention appears)
            "fastpath": {
                "dispatches": c["fastpath_dispatches"],
                "rows": c["fastpath_rows"],
                "lane_fraction": (
                    round(c["fastpath_dispatches"] / c["requests"], 4)
                    if c["requests"] else None),
            },
            # the cascade's operating point (ISSUE 17): class demand,
            # rows per stage, and what fraction of cheap-stage rows the
            # calibrated threshold sent on to f32 — the knob the
            # goodput-vs-accuracy frontier turns on
            "cascade": {
                "by_class": {k: v for k, v in
                             sorted(c["cascade_class"].items())},
                "stage_rows": {st: s for st, s in
                               sorted(c["cascade_stage_rows"].items())},
                "escalated_requests": c["cascade_escalated_requests"],
                "escalated_rows": c["cascade_escalated_rows"],
                "degraded_requests": c["cascade_degraded"],
                "escalation_fraction": cascade_escalation_fraction,
            },
            "fleet": {
                "failovers": c["failovers"],
                "failovers_total": sum(c["failovers"].values()),
                "last_failover": c["last_failover"],
                "hedges": c["hedges"],
                "hedge_wins": c["hedge_wins"],
                "replica_trips": sum(c["replica_trips"].values()),
                "replica_trips_by_replica": c["replica_trips"],
            },
            # the control loop's operating point (ISSUE 20): current
            # scale in actuator units, applied decisions by direction,
            # cooldown-suppressed decisions (why flaps stay zero),
            # ceiling-hit ticks (disclosed saturation), and the last
            # applied step's cost-model price
            "autoscale": {
                "scale": c["autoscale_scale"],
                "decisions": {k: v for k, v in
                              sorted(c["autoscale_decisions"].items())},
                "suppressed": c["autoscale_suppressed"],
                "saturated_ticks": c["autoscale_saturated"],
                "last_cost_chip_s": c["autoscale_last_cost"],
            },
            "resilience": {
                "deadline_shed_requests": c["deadline_shed_requests"],
                "deadline_shed_rows": c["deadline_shed_rows"],
                "bisect_splits": c["bisect_splits"],
                "poison_isolated_requests":
                    c["poison_isolated_requests"],
                "poison_isolated_rows": c["poison_isolated_rows"],
                "bisect_rescued_requests": c["bisect_rescued_requests"],
                "bisect_rescued_rows": c["bisect_rescued_rows"],
                "dispatch_error_requests": c["dispatch_error_requests"],
                "fetch_error_requests": c["fetch_error_requests"],
                "breaker_trips": c["breaker_trips"],
                "breaker_trips_by_version":
                    c["breaker_trips_by_version"],
                "rollbacks": c["rollbacks"],
                "last_rollback": c["last_rollback"],
            },
        }

    def record(self) -> dict:
        """The supervise-acceptable heartbeat record: a JSON-able dict
        with the conventional 'metric' key."""
        return {"metric": "serve_stats", **self.snapshot()}

    def heartbeat_line(self) -> str:
        return MetricsLogger.summary_line(self.record())


# -- Prometheus text exposition (ISSUE 9 satellite) ------------------------

# The p-keys utils.percentiles emits, as Prometheus quantile labels.
_PROM_QUANTILES = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}

# One-line # HELP text per series (ISSUE 10 satellite): scrapers AND
# humans read the exposition, and a bare # TYPE line tells neither what
# the number means. Every emitted dmnist_serve_* family gets a HELP
# line — names absent here fall back to a generated one, so a new
# series can never ship help-less.
_PROM_HELP = {
    "dmnist_serve_requests_total":
        "Requests served (computed fan-outs plus cache hits).",
    "dmnist_serve_rows_total": "Image rows served.",
    "dmnist_serve_batches_total": "Engine batches fetched.",
    "dmnist_serve_rejected_requests_total":
        "Requests shed at the queue watermark (503).",
    "dmnist_serve_rejected_rows_total":
        "Rows shed at the queue watermark.",
    "dmnist_serve_dispatched_rows_total":
        "Bucket slots executed on the device (incl. padding).",
    "dmnist_serve_padded_rows_total":
        "Executed bucket slots that were padding, not real rows.",
    "dmnist_serve_requests_per_second":
        "Request rate over the current metrics window.",
    "dmnist_serve_rows_per_second":
        "Row rate over the current metrics window.",
    "dmnist_serve_padding_waste_ratio":
        "Fraction of executed slots burned on padding.",
    "dmnist_serve_inflight_max":
        "Max dispatched-but-unfetched pipeline depth observed.",
    "dmnist_serve_queue_depth_max":
        "Max pending-row queue depth observed at batch record time.",
    "dmnist_serve_latency_ms":
        "End-to-end request latency quantiles, milliseconds.",
    "dmnist_serve_staging_ms":
        "Host staging (pad + device_put + enqueue) quantiles, ms.",
    "dmnist_serve_fetch_ms":
        "Blocking device-to-host fetch quantiles, milliseconds.",
    "dmnist_serve_bucket_dispatches_total":
        "Batches dispatched per compile bucket.",
    "dmnist_serve_version_requests_total":
        "Requests served per model version (canary separability).",
    "dmnist_serve_replica_batches_total":
        "Batches computed per fleet replica.",
    "dmnist_serve_dtype_batches_total":
        "Batches computed per serving precision.",
    "dmnist_serve_shadow_errors_total":
        "Shadow-candidate dispatch/fetch failures (swallowed).",
    "dmnist_serve_deadline_shed_requests_total":
        "Requests shed before dispatch on an expired deadline (504).",
    "dmnist_serve_bisect_splits_total":
        "Failed segments split in half for poison isolation.",
    "dmnist_serve_poison_isolated_requests_total":
        "Culprit requests isolated to a singleton dispatch.",
    "dmnist_serve_bisect_rescued_requests_total":
        "Cohort-mates that re-dispatched clean after a split.",
    "dmnist_serve_dispatch_error_requests_total":
        "Requests failed at dispatch without isolation.",
    "dmnist_serve_fetch_error_requests_total":
        "Requests failed by a batch fetch error.",
    "dmnist_serve_breaker_trips_total": "Circuit-breaker trips.",
    "dmnist_serve_breaker_version_trips_total":
        "Circuit-breaker trips attributed per model version.",
    "dmnist_serve_rollbacks_total":
        "Completed automatic rollbacks to a healthy resident.",
    "dmnist_serve_failovers_total":
        "Batches rescued on a sibling replica, by failure kind.",
    "dmnist_serve_hedges_total": "Hedged duplicate dispatches raced.",
    "dmnist_serve_hedge_wins_total":
        "Hedge races the duplicate won (tail bought back).",
    "dmnist_serve_replica_trips_total":
        "Per-replica circuit-breaker trips.",
    "dmnist_serve_stage_duration_ms":
        "Per-stage request durations derived from trace spans, ms.",
    "dmnist_serve_pending_rows": "Rows pending in the batcher queue.",
    "dmnist_serve_inflight_batches":
        "Dispatch segments popped but not yet fully resolved.",
    # prediction-cache front layer (ISSUE 10)
    "dmnist_serve_cache_hits_total":
        "Prediction-cache lookups served from a cached response.",
    "dmnist_serve_cache_hit_rows_total":
        "Rows served straight from the prediction cache.",
    "dmnist_serve_cache_misses_total":
        "Prediction-cache lookups that missed.",
    "dmnist_serve_cache_collapsed_total":
        "Identical concurrent misses collapsed onto one in-flight "
        "computation (single-flight followers).",
    "dmnist_serve_cache_inserts_total":
        "Computed responses inserted into the prediction cache.",
    "dmnist_serve_cache_evictions_total":
        "LRU evictions past the prediction-cache capacity.",
    "dmnist_serve_cache_invalidations_total":
        "Whole-cache invalidations (promote/rollback/dtype swap).",
    "dmnist_serve_cache_stale_drops_total":
        "Inserts or reads refused because the computing version no "
        "longer matched the live route.",
    "dmnist_serve_cache_hit_ratio":
        "Hits over lookups since process start (None until traffic).",
    "dmnist_serve_cache_entries": "Live prediction-cache entries.",
    "dmnist_serve_cache_inflight_keys":
        "Single-flight computations currently in flight.",
    "dmnist_serve_dedup_requests_total":
        "Intra-batch dedup riders resolved from a representative's "
        "dispatch.",
    "dmnist_serve_dedup_rows_total":
        "Device rows the intra-batch dedup did not dispatch.",
    # single-request fast lane (ISSUE 14)
    "dmnist_serve_fastpath_dispatches_total":
        "Requests served through the single-request bypass lane "
        "(dispatched on the caller's thread, no coalesce wait).",
    "dmnist_serve_fastpath_rows_total":
        "Rows served through the bypass lane.",
    "dmnist_serve_fastpath_lane_fraction":
        "Fraction of served requests that took the bypass lane.",
    "dmnist_serve_cache_expired_total":
        "Cache entries that aged past the TTL (expired hits count "
        "as misses).",
    # confidence-gated cascade (ISSUE 17)
    "dmnist_serve_cascade_requests_total":
        "Requests entering the cascade front, by accuracy class "
        "(X-Accuracy-Class: fast / balanced / exact).",
    "dmnist_serve_cascade_stage_rows_total":
        "Rows answered per cascade stage (the cheap dtype counts every "
        "stage-1 row, float32 only the escalated slice).",
    "dmnist_serve_cascade_escalated_requests_total":
        "Balanced requests with at least one row under the calibrated "
        "margin threshold (re-submitted to f32).",
    "dmnist_serve_cascade_escalated_rows_total":
        "Rows escalated to the f32 stage.",
    "dmnist_serve_cascade_escalation_fraction":
        "Escalated rows over cheap-stage rows: the fraction of "
        "stage-1 work the calibrated threshold sent on to f32.",
    "dmnist_serve_cascade_degraded_total":
        "Cascade-front requests served by the plain live route "
        "(no calibrated cascade on the live version).",
    # multi-tenant scheduler (ISSUE 18)
    "dmnist_serve_tenant_requests_total":
        "Requests admitted per tenant SLO class.",
    "dmnist_serve_tenant_rows_total":
        "Rows admitted per tenant SLO class.",
    "dmnist_serve_tenant_dispatched_rows_total":
        "Rows the global scheduler granted per tenant (the WFQ "
        "service share's numerator).",
    "dmnist_serve_tenant_sheds_total":
        "Requests shed per tenant by kind: quota (429), watermark "
        "(503), deadline (504 / infeasible-by-cost-model).",
    "dmnist_serve_tenant_cache_hits_total":
        "Would-be sheds rescued by a prediction-cache probe (the "
        "cache-aware shed path; never quota-charged).",
    "dmnist_serve_tenant_dispatch_share":
        "Tenant's fraction of all scheduler-granted rows; divide by "
        "the weight share for the WFQ fairness ratio.",
    "dmnist_serve_tenant_slo_attainment":
        "Fraction of a tenant's completed requests that finished "
        "inside their SLO-class deadline.",
    "dmnist_serve_tenant_latency_ms":
        "Per-tenant end-to-end latency quantiles (enqueue at the "
        "global scheduler to future resolution), milliseconds.",
    "dmnist_serve_model_requests_total":
        "Requests routed per catalog model.",
    "dmnist_serve_model_dispatched_rows_total":
        "Rows the scheduler granted per catalog model.",
    # autoscaling control loop (ISSUE 20)
    "dmnist_serve_autoscale_scale":
        "Current scale in actuator units (in-flight window slots on a "
        "single host, active workers behind the gateway).",
    "dmnist_serve_autoscale_decisions_total":
        "Actuated scale decisions by direction (grow / shrink).",
    "dmnist_serve_autoscale_suppressed_total":
        "Scale decisions suppressed by the cooldown window (the "
        "anti-flap counter; nonzero under square-wave load is the "
        "hysteresis doing its job).",
    "dmnist_serve_autoscale_saturated_total":
        "Control ticks that wanted to grow past the configured "
        "ceiling — disclosed saturation, not silent queueing.",
    "dmnist_serve_autoscale_last_cost_chip_seconds":
        "Priced cost of the most recent decision: chip-seconds per "
        "second bought (positive) or released (negative), in the "
        "actuator's disclosed cost basis.",
}


def _prom_help(name: str) -> str:
    return _PROM_HELP.get(
        name, name.removeprefix("dmnist_serve_").replace("_", " ") + ".")


def _prom_escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_line(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(f'{k}="{_prom_escape(v)}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


def prometheus_exposition(snapshot: dict,
                          trace_stages: dict = None,
                          gauges: dict = None,
                          cache: dict = None) -> str:
    """Flatten a ServeMetrics snapshot() into Prometheus text format
    (`GET /metrics?format=prometheus`, or an `Accept: text/plain`
    scrape): stably-named counters/gauges/summaries with `# HELP` +
    `# TYPE` lines, derived from the SAME snapshot the JSON surface
    serves — a scrape surface for the fleet story without a second
    accounting path. `trace_stages` (Tracer.snapshot()["stages"],
    optional) adds the per-stage duration histograms derived from the
    ISSUE 9 spans; `gauges` adds point-in-time pipeline gauges (queue
    depth, in-flight window) the snapshot itself does not carry;
    `cache` (PredictionCache.stats(), optional) adds the ISSUE 10
    hit/miss/collapse/evict counters and hit ratio. None-valued
    samples (empty percentile windows, a pre-traffic hit ratio) are
    skipped, never emitted as 0."""
    out: list[str] = []

    def emit(name: str, mtype: str, samples) -> None:
        rows = [(labels, v) for labels, v in samples if v is not None]
        if not rows:
            return
        out.append(f"# HELP {name} {_prom_help(name)}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, v in rows:
            out.append(_prom_line(name, labels, v))

    def summary(name: str, pct: dict, count=None) -> None:
        rows = [({"quantile": q}, pct.get(p))
                for p, q in _PROM_QUANTILES.items()]
        if all(v is None for _, v in rows):
            return
        emit(name, "summary", rows)
        if count is not None:
            out.append(_prom_line(name + "_count", {}, count))

    s = snapshot
    res = s.get("resilience", {})
    fleet = s.get("fleet", {})
    emit("dmnist_serve_requests_total", "counter",
         [({}, s.get("requests"))])
    emit("dmnist_serve_rows_total", "counter", [({}, s.get("rows"))])
    emit("dmnist_serve_batches_total", "counter",
         [({}, s.get("batches"))])
    emit("dmnist_serve_rejected_requests_total", "counter",
         [({}, s.get("rejected_requests"))])
    emit("dmnist_serve_rejected_rows_total", "counter",
         [({}, s.get("rejected_rows"))])
    emit("dmnist_serve_dispatched_rows_total", "counter",
         [({}, s.get("dispatched_rows"))])
    emit("dmnist_serve_padded_rows_total", "counter",
         [({}, s.get("padded_rows"))])
    emit("dmnist_serve_requests_per_second", "gauge",
         [({}, s.get("requests_per_sec"))])
    emit("dmnist_serve_rows_per_second", "gauge",
         [({}, s.get("rows_per_sec"))])
    emit("dmnist_serve_padding_waste_ratio", "gauge",
         [({}, s.get("padding_waste_ratio"))])
    emit("dmnist_serve_inflight_max", "gauge",
         [({}, s.get("inflight_max"))])
    emit("dmnist_serve_queue_depth_max", "gauge",
         [({}, s.get("queue_depth_max"))])
    summary("dmnist_serve_latency_ms", s.get("latency_ms", {}),
            count=s.get("requests"))
    summary("dmnist_serve_staging_ms", s.get("staging_ms", {}))
    summary("dmnist_serve_fetch_ms", s.get("fetch_ms", {}))
    emit("dmnist_serve_bucket_dispatches_total", "counter",
         [({"bucket": b}, n)
          for b, n in s.get("bucket_dispatches", {}).items()])
    emit("dmnist_serve_version_requests_total", "counter",
         [({"version": v}, vs.get("requests"))
          for v, vs in s.get("by_version", {}).items()])
    emit("dmnist_serve_replica_batches_total", "counter",
         [({"replica": r}, rs.get("batches"))
          for r, rs in s.get("by_replica", {}).items()])
    emit("dmnist_serve_dtype_batches_total", "counter",
         [({"dtype": d}, ds.get("batches"))
          for d, ds in s.get("by_dtype", {}).items()])
    emit("dmnist_serve_shadow_errors_total", "counter",
         [({}, s.get("shadow_errors"))])
    # resilience (ISSUE 5) + fleet (ISSUE 6) counters
    emit("dmnist_serve_deadline_shed_requests_total", "counter",
         [({}, res.get("deadline_shed_requests"))])
    emit("dmnist_serve_bisect_splits_total", "counter",
         [({}, res.get("bisect_splits"))])
    emit("dmnist_serve_poison_isolated_requests_total", "counter",
         [({}, res.get("poison_isolated_requests"))])
    emit("dmnist_serve_bisect_rescued_requests_total", "counter",
         [({}, res.get("bisect_rescued_requests"))])
    emit("dmnist_serve_dispatch_error_requests_total", "counter",
         [({}, res.get("dispatch_error_requests"))])
    emit("dmnist_serve_fetch_error_requests_total", "counter",
         [({}, res.get("fetch_error_requests"))])
    emit("dmnist_serve_breaker_trips_total", "counter",
         [({}, res.get("breaker_trips"))])
    emit("dmnist_serve_breaker_version_trips_total", "counter",
         [({"version": v}, n) for v, n in
          res.get("breaker_trips_by_version", {}).items()])
    emit("dmnist_serve_rollbacks_total", "counter",
         [({}, res.get("rollbacks"))])
    emit("dmnist_serve_failovers_total", "counter",
         [({"kind": k}, n)
          for k, n in fleet.get("failovers", {}).items()])
    emit("dmnist_serve_hedges_total", "counter",
         [({}, fleet.get("hedges"))])
    emit("dmnist_serve_hedge_wins_total", "counter",
         [({}, fleet.get("hedge_wins"))])
    emit("dmnist_serve_replica_trips_total", "counter",
         [({"replica": r}, n) for r, n in
          fleet.get("replica_trips_by_replica", {}).items()])
    # Prediction-cache front layer (ISSUE 10): the cache's own
    # counters (hit/miss/collapse/insert/evict/invalidate/stale) plus
    # hit ratio, and the batcher's dedup counters from the snapshot.
    dd = s.get("dedup", {})
    emit("dmnist_serve_dedup_requests_total", "counter",
         [({}, dd.get("requests"))])
    emit("dmnist_serve_dedup_rows_total", "counter",
         [({}, dd.get("rows"))])
    # single-request fast lane (ISSUE 14): the lane split
    fp = s.get("fastpath", {})
    emit("dmnist_serve_fastpath_dispatches_total", "counter",
         [({}, fp.get("dispatches"))])
    emit("dmnist_serve_fastpath_rows_total", "counter",
         [({}, fp.get("rows"))])
    emit("dmnist_serve_fastpath_lane_fraction", "gauge",
         [({}, fp.get("lane_fraction"))])
    # confidence-gated cascade (ISSUE 17): class demand, per-stage
    # rows, escalation volume and the degrade counter
    ca = s.get("cascade", {})
    emit("dmnist_serve_cascade_requests_total", "counter",
         [({"accuracy_class": cls}, n)
          for cls, n in sorted(ca.get("by_class", {}).items())])
    emit("dmnist_serve_cascade_stage_rows_total", "counter",
         [({"stage": st}, v.get("rows"))
          for st, v in sorted(ca.get("stage_rows", {}).items())])
    emit("dmnist_serve_cascade_escalated_requests_total", "counter",
         [({}, ca.get("escalated_requests"))])
    emit("dmnist_serve_cascade_escalated_rows_total", "counter",
         [({}, ca.get("escalated_rows"))])
    emit("dmnist_serve_cascade_escalation_fraction", "gauge",
         [({}, ca.get("escalation_fraction"))])
    emit("dmnist_serve_cascade_degraded_total", "counter",
         [({}, ca.get("degraded_requests"))])
    # multi-tenant scheduler (ISSUE 18): per-tenant demand/service/
    # shed split and per-model catalog demand. Labels come from the
    # operator-configured SLO-class names and catalog model names, so
    # cardinality is bounded by configuration, not by traffic.
    bt = s.get("by_tenant", {})
    emit("dmnist_serve_tenant_requests_total", "counter",
         [({"tenant": t}, ts.get("requests"))
          for t, ts in bt.items()])
    emit("dmnist_serve_tenant_rows_total", "counter",
         [({"tenant": t}, ts.get("rows")) for t, ts in bt.items()])
    emit("dmnist_serve_tenant_dispatched_rows_total", "counter",
         [({"tenant": t}, ts.get("dispatched_rows"))
          for t, ts in bt.items()])
    emit("dmnist_serve_tenant_sheds_total", "counter",
         [({"tenant": t, "kind": kind}, ts.get(f"{kind}_sheds"))
          for t, ts in bt.items()
          for kind in ("quota", "watermark", "deadline")])
    emit("dmnist_serve_tenant_cache_hits_total", "counter",
         [({"tenant": t}, ts.get("cache_hits"))
          for t, ts in bt.items()])
    emit("dmnist_serve_tenant_dispatch_share", "gauge",
         [({"tenant": t}, ts.get("dispatch_share"))
          for t, ts in bt.items()])
    emit("dmnist_serve_tenant_slo_attainment", "gauge",
         [({"tenant": t}, ts.get("slo_attainment"))
          for t, ts in bt.items()])
    emit("dmnist_serve_tenant_latency_ms", "summary",
         [({"tenant": t, "quantile": q}, ts.get("latency_ms", {}).get(p))
          for t, ts in bt.items()
          for p, q in _PROM_QUANTILES.items()])
    bm = s.get("by_model", {})
    emit("dmnist_serve_model_requests_total", "counter",
         [({"model": m}, ms.get("requests"))
          for m, ms in bm.items()])
    emit("dmnist_serve_model_dispatched_rows_total", "counter",
         [({"model": m}, ms.get("dispatched_rows"))
          for m, ms in bm.items()])
    # autoscaling control loop (ISSUE 20): current scale, decision
    # volume by direction, the cooldown/ceiling disclosures, and the
    # priced cost of the last actuation.
    asc = s.get("autoscale", {})
    emit("dmnist_serve_autoscale_scale", "gauge",
         [({}, asc.get("scale") or None)])
    emit("dmnist_serve_autoscale_decisions_total", "counter",
         [({"direction": d}, n)
          for d, n in asc.get("decisions", {}).items()])
    emit("dmnist_serve_autoscale_suppressed_total", "counter",
         [({}, asc.get("suppressed"))])
    emit("dmnist_serve_autoscale_saturated_total", "counter",
         [({}, asc.get("saturated_ticks"))])
    emit("dmnist_serve_autoscale_last_cost_chip_seconds", "gauge",
         [({}, asc.get("last_cost_chip_s") or None)])
    if cache:
        emit("dmnist_serve_cache_hits_total", "counter",
             [({}, cache.get("hits"))])
        emit("dmnist_serve_cache_hit_rows_total", "counter",
             [({}, cache.get("hit_rows"))])
        emit("dmnist_serve_cache_misses_total", "counter",
             [({}, cache.get("misses"))])
        emit("dmnist_serve_cache_collapsed_total", "counter",
             [({}, cache.get("collapsed"))])
        emit("dmnist_serve_cache_inserts_total", "counter",
             [({}, cache.get("inserts"))])
        emit("dmnist_serve_cache_evictions_total", "counter",
             [({}, cache.get("evictions"))])
        emit("dmnist_serve_cache_invalidations_total", "counter",
             [({}, cache.get("invalidations"))])
        emit("dmnist_serve_cache_stale_drops_total", "counter",
             [({}, cache.get("stale_drops"))])
        emit("dmnist_serve_cache_expired_total", "counter",
             [({}, cache.get("expired"))])
        emit("dmnist_serve_cache_entries", "gauge",
             [({}, cache.get("entries"))])
        emit("dmnist_serve_cache_inflight_keys", "gauge",
             [({}, cache.get("inflight_keys"))])
        emit("dmnist_serve_cache_hit_ratio", "gauge",
             [({}, cache.get("hit_ratio"))])
    for name, value in (gauges or {}).items():
        emit(f"dmnist_serve_{name}", "gauge", [({}, value)])
    # Per-stage duration histograms derived from the ISSUE 9 spans —
    # cumulative buckets per the Prometheus histogram contract.
    if trace_stages:
        name = "dmnist_serve_stage_duration_ms"
        out.append(f"# HELP {name} {_prom_help(name)}")
        out.append(f"# TYPE {name} histogram")
        for stage, h in sorted(trace_stages.items()):
            cum = 0
            for le, count in h["buckets"].items():
                cum += count
                out.append(_prom_line(name + "_bucket",
                                      {"stage": stage, "le": le}, cum))
            out.append(_prom_line(name + "_sum", {"stage": stage},
                                  h["sum_ms"]))
            out.append(_prom_line(name + "_count", {"stage": stage},
                                  h["count"]))
    return "\n".join(out) + "\n"


_GATEWAY_PROM_HELP = {
    "dmnist_gateway_requests_total":
        "Requests admitted by the gateway's routing layer.",
    "dmnist_gateway_routed_affinity_total":
        "Requests routed to their consistent-hash ring owner (the "
        "sharded-cache path).",
    "dmnist_gateway_routed_balanced_total":
        "Requests routed by the cost-aware least-loaded fallback "
        "(no computable ring key, or owners dead/cooled).",
    "dmnist_gateway_failovers_total":
        "Mid-request worker failures that entered the one-redispatch "
        "failover path.",
    "dmnist_gateway_failover_rescued_total":
        "Failovers whose redispatch to the next ring owner answered.",
    "dmnist_gateway_backpressure_503_total":
        "Requests shed because the target worker's in-flight window "
        "was full (spilling an affinity key would duplicate its "
        "cache entry).",
    "dmnist_gateway_paused_503_total":
        "Requests shed waiting out a fleet-promote admission pause.",
    "dmnist_gateway_mixed_epoch_rejected_total":
        "Worker replies rejected because their X-Cluster-Epoch did "
        "not match the epoch the request was admitted under (must "
        "stay zero; the two-phase promote barrier makes the path "
        "unreachable).",
    "dmnist_gateway_worker_deaths_total":
        "Workers removed from the ring after dying (process exit or "
        "connection refused).",
    "dmnist_gateway_promotes_total":
        "Completed fleet-wide two-phase promotes.",
    "dmnist_gateway_cluster_epoch":
        "The gateway's current cluster epoch (bumped once per "
        "fleet-wide promote flip).",
    "dmnist_gateway_workers": "Workers spawned (alive or dead).",
    "dmnist_gateway_workers_active": "Workers in the dispatch set.",
    "dmnist_gateway_worker_inflight":
        "Requests currently dispatched to each worker.",
    "dmnist_gateway_worker_dispatched_total":
        "Requests each worker answered (including rescues).",
    "dmnist_gateway_worker_rescued_total":
        "Failover rescues each worker absorbed.",
    "dmnist_gateway_worker_failures_total":
        "Failed round trips attributed to each worker.",
}


def gateway_prometheus_exposition(snapshot: dict) -> str:
    """Flatten Gateway.snapshot() into Prometheus text format — the
    `dmnist_gateway_*` series (ISSUE 19), same discipline as
    prometheus_exposition above: stable names, # HELP/# TYPE pairs,
    None-valued samples skipped. Per-worker series are labelled
    worker=<rid> so a dashboard can see the ring's shard balance and
    which worker absorbed a failover."""
    out: list[str] = []

    def emit(name: str, mtype: str, samples) -> None:
        rows = [(labels, v) for labels, v in samples if v is not None]
        if not rows:
            return
        help_text = _GATEWAY_PROM_HELP.get(
            name,
            name.removeprefix("dmnist_gateway_").replace("_", " ") + ".")
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, v in rows:
            out.append(_prom_line(name, labels, v))

    s = snapshot
    for key in ("requests", "routed_affinity", "routed_balanced",
                "failovers", "failover_rescued", "backpressure_503",
                "paused_503", "mixed_epoch_rejected", "worker_deaths",
                "promotes"):
        emit(f"dmnist_gateway_{key}_total", "counter", [({}, s.get(key))])
    emit("dmnist_gateway_cluster_epoch", "gauge",
         [({}, s.get("cluster_epoch"))])
    emit("dmnist_gateway_workers", "gauge", [({}, s.get("workers"))])
    emit("dmnist_gateway_workers_active", "gauge",
         [({}, s.get("workers_active"))])
    per = s.get("per_worker") or []
    emit("dmnist_gateway_worker_inflight", "gauge",
         [({"worker": w["worker"]}, w.get("inflight")) for w in per])
    emit("dmnist_gateway_worker_dispatched_total", "counter",
         [({"worker": w["worker"]}, w.get("dispatched")) for w in per])
    emit("dmnist_gateway_worker_rescued_total", "counter",
         [({"worker": w["worker"]}, w.get("rescued")) for w in per])
    emit("dmnist_gateway_worker_failures_total", "counter",
         [({"worker": w["worker"]}, w.get("failures")) for w in per])
    return "\n".join(out) + "\n"
