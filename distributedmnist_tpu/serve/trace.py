"""End-to-end request tracing (ISSUE 9): request-scoped span trees,
tail attribution, and the per-stage duration surface behind /metrics.

Everything the serving stack measured before this module is AGGREGATE
(ServeMetrics percentiles, per-version/replica/dtype populations).
Aggregates cannot answer the question an operator actually asks when
p99 spikes: where did THIS slow request spend its budget — the
coalescing queue, host staging, device compute, the blocking fetch, a
failover rescue, a bisection retry? Clockwork's core argument
(PAPERS.md) is that predictable serving requires attributing every
millisecond of a request's latency to a named pipeline stage; Clipper's
shed-at-the-front-door stance only works if the operator can see WHICH
stage is saturating. This module is that per-request layer:

- A **trace** is one request's span tree: a root `request` span plus
  every pipeline stage the request crossed. Batch-level spans
  (coalesce, dispatch, the in-flight window, fetch) carry the request
  ids of every cohort member and appear in each member's tree — the
  honest model, since a batched stage IS shared.
- **Spans** are recorded by hooks woven through the batcher, engine,
  router, fleet and resilience paths. With no tracer installed (every
  production process — the serve/faults.py idiom) each hook is one
  module-global None check; `bench.py serve`'s headline runs tracer-off
  and must stay within run-to-run noise of pre-ISSUE-9 records.
- **Tail attribution is the point**, so retention is head sampling
  (deterministic per-request draw) PLUS always-keep exemplars: errored
  and over-SLO requests land in their own bounded ring and can never be
  the sampled-out ones. Both rings are bounded deques — a tracer left
  on for a week costs fixed memory.
- Every completed span also feeds a **per-stage duration histogram**
  (fixed log-spaced ms buckets), exported via snapshot() and flattened
  into the Prometheus exposition — the fleet-scrape view derived from
  the same spans as the per-request trees, not a second accounting
  path.

Span discipline (lint rule DML007): in serve/ every `begin_span` call
is immediately followed by a try whose `finally` calls `end_span` — an
exception mid-stage must not leave an unclosed span skewing
attribution. Spans whose begin and end live on different threads
(queue wait, the dispatched-but-unfetched window) are synthesized as
already-closed intervals via `add_span` from monotonic stamps both
sides already hold, so nothing can be left open across a thread hop.

All clocks are monotonic (DML004); every internal lock comes from
analysis/locks so the ISSUE 8 sanitizer covers this module too.

Span name table (stage -> what it times -> mechanism):

    request                 submit to future resolution (the root)
    queue.wait              submit to pop (coalescing + backpressure
                            delay; `shed=True` when the deadline
                            expired queued — ISSUE 5)
    batch.coalesce          one drain's coalesce window (batch-level)
    batch.plan              the cost-model batch former (ISSUE 4)
    batch.pending           pop to this segment's dispatch begin (plan
                            + bookkeeping + window-slot wait for later
                            segments of a split drain)
    batch.dispatch          batcher dispatch site incl. the failpoint
    engine.staging          pad + device_put + enqueue (ISSUE 1/2)
    engine.enqueued         dispatched-but-unfetched window: device
                            compute overlapping later staging (the
                            ISSUE 2 pipelining, visible as overlap in
                            chrome://tracing)
    engine.fetch            the blocking device->host value fetch
    batch.fanout            fetch-done to this request's resolution
    router.shadow           shadow duplicate dispatch (ISSUE 3)
    bisect.split            a failed cohort split in two (ISSUE 5)
    bisect.dispatch         one bisection sub-dispatch
    deadline.shed           shed-before-dispatch marker (ISSUE 5)
    fleet.failover.dispatch rescue dispatch on a sibling (ISSUE 6)
    fleet.failover.fetch    fetch-side rescue: redispatch + fetch
    fleet.hedge             the hedged-tail race (winner tagged)
    fleet.hedge.primary     the overdue primary's fetch arm
    fleet.hedge.duplicate   the duplicate's dispatch + fetch arm
    cache.lookup            the prediction-cache front's content-hash
                            lookup (ISSUE 10; collapsed=True when the
                            miss joined an in-flight leader)
    cache.hit               served from the cache — zero pipeline work
    cache.collapse          a single-flight follower's wait on its
                            leader's computation
    batch.dedup             intra-batch dedup riders collapsed onto a
                            representative dispatch (zero-width marker)
    fastpath                the single-request bypass lane's inline
                            dispatch+fetch on the caller's thread
                            (ISSUE 14; staging/fetch children nest
                            inside it and claim their own time)
    fastpath.admit          submit to lane dispatch begin (validation
                            + the atomic lane decision under the
                            queue lock)
    cascade.stage           one cascade stage's whole window (ISSUE 17):
                            submit (or escalation) to stage resolution;
                            tagged stage=<dtype>, rows, and — on the
                            cheap stage — how many rows escalated
    cascade.escalate        zero-width escalation marker: the margin
                            partition's decision point, tagged with the
                            calibrated threshold and escalated rows
    gateway.route           gateway process (ISSUE 19): the routing
                            decision — ring-affinity or least-loaded
                            pick, including any backpressure/promote-
                            pause wait
    gateway.dispatch        the worker HTTP round trip; tagged
                            worker=<rid> and worker_trace_id=<the
                            worker's X-Trace-Id>, while the worker's
                            own trace carries the gateway's id from
                            the X-Gateway-Trace-Id request header —
                            cross-process correlation from both sides
    gateway.failover        the one rescue redispatch after a worker
                            died mid-request
"""

from __future__ import annotations

import hashlib
import itertools
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from distributedmnist_tpu.analysis.locks import make_lock

# Per-stage histogram bucket upper bounds, milliseconds (log-spaced;
# the final implicit bucket is +Inf). Shared with the Prometheus
# exposition, which emits them cumulatively per the histogram contract.
STAGE_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                    100.0, 250.0, 1000.0)

# Span name -> (attribution stage, claim priority). Higher priority
# claims wall-clock first, so a rescue nested inside an engine.fetch
# span is blamed on the rescue, not double-counted as fetch. Names
# absent here (the request root, batch.coalesce/batch.plan — pure
# context, they overlap queue.wait) never claim time.
STAGE_OF = {
    "queue.wait": ("queue", 20),
    "batch.pending": ("pending", 12),
    "engine.staging": ("staging", 40),
    "batch.dispatch": ("staging", 10),
    "engine.enqueued": ("device", 40),
    "engine.fetch": ("fetch", 30),
    "batch.fanout": ("fanout", 15),
    "router.shadow": ("shadow", 50),
    "bisect.dispatch": ("bisect", 60),
    "deadline.shed": ("shed", 60),
    "fleet.failover.dispatch": ("rescue", 80),
    "fleet.failover.fetch": ("rescue", 80),
    "fleet.hedge": ("hedge", 70),
    "fleet.hedge.primary": ("hedge", 75),
    "fleet.hedge.duplicate": ("hedge", 75),
    # prediction-cache front layer (ISSUE 10): a hit's whole budget is
    # the lookup; a collapsed follower's is the wait on its leader
    "cache.lookup": ("cache", 90),
    "cache.hit": ("cache", 90),
    "cache.collapse": ("cache", 85),
    # single-request bypass lane (ISSUE 14): `fastpath` wraps the whole
    # inline dispatch+fetch at LOW priority so the nested staging/fetch
    # stages claim their own microseconds and the lane keeps only the
    # bookkeeping remainder; `fastpath.admit` closes the submit-to-
    # dispatch gap so attribution of a lane request has no residue
    "fastpath": ("fastpath", 8),
    "fastpath.admit": ("fastpath", 18),
    # confidence-gated cascade (ISSUE 17): stage spans wrap the inner
    # pipeline's spans at LOW priority (the nested queue/staging/fetch
    # stages claim their own time; the cascade keeps the margin math +
    # callback bookkeeping remainder); the escalate marker is
    # zero-width, priority only for deterministic attribution order
    "cascade.stage": ("cascade", 5),
    "cascade.escalate": ("cascade", 6),
    # gateway process (ISSUE 19): route = ring/least-loaded pick +
    # admission (backpressure/pause waits land here); dispatch = the
    # worker round trip, tagged with the worker's own X-Trace-Id so
    # the two processes' traces name each other; failover = the one
    # rescue redispatch after a mid-request worker death, high
    # priority like the fleet's rescues
    "gateway.route": ("route", 30),
    "gateway.dispatch": ("upstream", 20),
    "gateway.failover": ("rescue", 80),
}


class Span:
    """One open span: identity, interval start, parent link, tags.
    Recorded into the tracer (and its stage histogram) only at end —
    an abandoned Span object is garbage-collected, never exported, and
    counted by the open-span gauge until ended."""

    __slots__ = ("tracer", "id", "parent", "name", "t0", "tid",
                 "tags", "rids", "ended", "exc0")

    def __init__(self, tracer, sid, parent, name, t0, tid, tags, rids,
                 exc0=None):
        self.tracer = tracer
        self.id = sid
        self.parent = parent
        self.name = name
        self.t0 = t0
        self.tid = tid
        self.tags = tags
        self.rids = rids
        self.ended = False
        # The AMBIENT exception at begin time: failure-handling code
        # (bisection, failover rescues) begins spans INSIDE an except
        # handler, where sys.exc_info() reports the exception being
        # handled — only a NEW exception at end time marks this span
        # errored, not the enclosing failure it exists to repair.
        self.exc0 = exc0


def _interval_merge(iv):
    """Sorted, merged [lo, hi) interval list."""
    out = []
    for a, b in sorted(iv):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _interval_subtract(iv, taken):
    """`iv` minus `taken` (both merged-sorted)."""
    out = []
    for a, b in iv:
        cur = a
        for ta, tb in taken:
            if tb <= cur:
                continue
            if ta >= b:
                break
            if ta > cur:
                out.append((cur, min(ta, b)))
            cur = max(cur, tb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def _interval_total(iv):
    return sum(b - a for a, b in iv)


def attribute_stages(trace: dict) -> dict:
    """Blame a finished trace's wall clock on named stages.

    Each moment of the request's [start, end) interval is assigned to
    the highest-priority stage whose span covers it (STAGE_OF), so
    nested spans (a rescue inside a fetch, staging inside a dispatch)
    never double-count. What no stage claims is the RESIDUE — reported,
    never hidden: `bench.py serve --trace` holds the residue of every
    over-SLO request under 5% (the acceptance bar), and a growing
    residue means a new pipeline stage is missing its span."""
    root = next(s for s in trace["spans"] if s["name"] == "request")
    t_lo = root["t0"]
    t_hi = root["t0"] + root["dur"]
    total = max(t_hi - t_lo, 1e-12)
    by_stage: dict[str, list] = {}
    prio: dict[str, int] = {}
    for s in trace["spans"]:
        entry = STAGE_OF.get(s["name"])
        if entry is None:
            continue
        stage, p = entry
        a = max(s["t0"], t_lo)
        b = min(s["t0"] + s["dur"], t_hi)
        if b > a:
            by_stage.setdefault(stage, []).append((a, b))
        prio[stage] = max(prio.get(stage, 0), p)
    assigned: list = []
    stages_ms = {}
    for stage in sorted(by_stage, key=lambda st: -prio[st]):
        free = _interval_subtract(_interval_merge(by_stage[stage]),
                                  assigned)
        stages_ms[stage] = _interval_total(free) * 1e3
        assigned = _interval_merge(assigned + free)
    covered = _interval_total(assigned)
    return {
        "total_ms": total * 1e3,
        "stages_ms": stages_ms,
        "residue_ms": max(total - covered, 0.0) * 1e3,
        "attributed_frac": min(covered / total, 1.0),
    }


class Tracer:
    """Request-scoped span collection with bounded retention.

    start_request/finish_request bracket each admitted request; spans
    are recorded via begin_span/end_span (same-thread stages) or
    add_span (already-measured intervals). Retention: errored and
    over-SLO traces always land in the exemplar ring; the rest pass a
    deterministic head-sampling draw into the main ring. Both rings are
    bounded deques. Thread-safe; the single internal lock is never held
    while calling out."""

    def __init__(self, capacity: int = 256, sample: float = 1.0,
                 slo_ms: Optional[float] = None, seed: int = 0,
                 exemplar_capacity: Optional[int] = None,
                 live_cap: int = 8192):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        if slo_ms is not None and slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        self.capacity = capacity
        self.sample = sample
        self.slo_ms = slo_ms
        self.seed = seed
        self._lock = make_lock("trace.tracer")
        self._tls = threading.local()
        self._ids = itertools.count(1)
        self._ring: deque = deque(maxlen=capacity)
        self._exemplars: deque = deque(
            maxlen=exemplar_capacity if exemplar_capacity is not None
            else max(capacity // 2, 16))
        self._live: "OrderedDict[int, dict]" = OrderedDict()
        self._live_cap = live_cap
        self._recent: "OrderedDict[str, dict]" = OrderedDict()
        self._recent_cap = 512
        self._stages: dict[str, list] = {}   # name -> [count, sum_ms,
        #                                      per-bucket counts + inf]
        self._open = 0
        self._started = 0
        self._finished = 0
        self._kept_sampled = 0
        self._kept_exemplar = 0
        self._sampled_out = 0
        self._aborted = 0
        self._dropped_live = 0

    # -- per-thread span stack (parent inference) -------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[tuple]:
        """(span_id, rids) of the innermost open span on THIS thread —
        the explicit parent ref for spans begun on a spawned thread
        (the fleet's hedge arms)."""
        st = self._stack()
        if not st:
            return None
        top = st[-1]
        return (top.id, top.rids)

    # -- request lifecycle -------------------------------------------------

    def start_request(self, rid: int, rows: int = 1,
                      deadline_s: Optional[float] = None,
                      t0: Optional[float] = None) -> str:
        """Open a trace for an ADMITTED request; returns its trace id
        (the X-Trace-Id header value). Called by the batcher BEFORE the
        queue insert, so pop-side spans always find the live trace.
        `t0` is the request's enqueue stamp — the root span starts
        exactly where the queue.wait child does, so no child can ever
        precede its root."""
        trace_id = f"{rid:08x}"
        with self._lock:
            self._started += 1
            if len(self._live) >= self._live_cap:
                # A request whose future never resolves must not grow
                # the live table without bound: drop the oldest open
                # trace (counted — silence would read as coverage).
                self._live.popitem(last=False)
                self._dropped_live += 1
            self._live[rid] = {
                "trace_id": trace_id,
                "rid": rid,
                "t0": t0 if t0 is not None else time.monotonic(),
                "rows": rows,
                "deadline": deadline_s,
                "spans": [],
            }
        return trace_id

    def abort_request(self, rid: int) -> None:
        """The submit was refused AFTER start_request (queue watermark,
        stopped batcher): the request never entered the pipeline, so it
        has no trace to keep."""
        with self._lock:
            if self._live.pop(rid, None) is not None:
                self._aborted += 1

    def finish_request(self, rid: int, error=None,
                       t_end: Optional[float] = None) -> None:
        """Close the trace: synthesize the root `request` span, decide
        retention (exemplar for errored/over-SLO, else the sampling
        draw), and make the stage breakdown available for Server-Timing
        lookups. Callers finish BEFORE resolving the request's future,
        so a client that has seen its result can immediately read the
        finished trace. `t_end` pins the root's end to a stamp the
        caller already holds (the fast lane's completion point —
        ISSUE 14): a root that ends a descheduling-blip later than its
        last child would charge pure bookkeeping to the residue, and
        the lane's attribution bar is exactly about leaving none."""
        now = t_end if t_end is not None else time.monotonic()
        with self._lock:
            acc = self._live.pop(rid, None)
            if acc is None:
                return
            dur = max(now - acc["t0"], 0.0)
            root = {
                "id": next(self._ids),
                "parent": None,
                "name": "request",
                "t0": acc["t0"],
                "dur": dur,
                "tid": "request",
                "rids": [rid],
                "status": "error" if error is not None else "ok",
                "tags": ({"rows": acc["rows"]} if error is None else
                         {"rows": acc["rows"],
                          "error": type(error).__name__}),
            }
            self._stage_record_locked("request", dur * 1e3)
            dur_ms = dur * 1e3
            over_slo = self.slo_ms is not None and dur_ms > self.slo_ms
            trace = {
                "trace_id": acc["trace_id"],
                "rid": rid,
                "t0": acc["t0"],
                "duration_ms": dur_ms,
                "status": root["status"],
                "over_slo": over_slo,
                "spans": [root] + acc["spans"],
            }
            self._finished += 1
            if root["status"] == "error" or over_slo:
                self._exemplars.append(trace)
                self._kept_exemplar += 1
            elif self._sampled(rid):
                self._ring.append(trace)
                self._kept_sampled += 1
            else:
                self._sampled_out += 1
        # Breakdown computed OUTSIDE the lock (interval math over a
        # handful of spans — cheap, but the lock is hot-path-adjacent).
        att = attribute_stages(trace)
        with self._lock:
            self._recent[acc["trace_id"]] = {
                "total_ms": att["total_ms"],
                "stages_ms": att["stages_ms"],
                "residue_ms": att["residue_ms"],
                "over_slo": over_slo,
                "status": root["status"],
            }
            while len(self._recent) > self._recent_cap:
                self._recent.popitem(last=False)

    def _sampled(self, rid: int) -> bool:
        # Deterministic per-request draw (the faults.py content-hash
        # idiom): the same request keeps the same verdict across runs,
        # so sampled bench replays are reproducible.
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = hashlib.sha256(f"{self.seed}:trace:{rid}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < self.sample

    # -- span recording ----------------------------------------------------

    def begin_span(self, name: str, rids=None, ctx=None, **tags) -> Span:
        """Open a span on this thread. Parent and request ids inherit
        from the innermost open span unless `rids` (explicit request
        set) or `ctx` (a current() ref from the spawning thread) is
        given. MUST be closed via end_span in a try/finally — lint rule
        DML007 enforces the shape in serve/."""
        st = self._stack()
        if ctx is not None:
            parent, inherited = ctx
        elif st:
            parent, inherited = st[-1].id, st[-1].rids
        else:
            parent, inherited = None, ()
        sp = Span(self, next(self._ids), parent, name, time.monotonic(),
                  threading.current_thread().name,
                  {k: v for k, v in tags.items() if v is not None},
                  tuple(rids) if rids is not None else tuple(inherited),
                  exc0=sys.exc_info()[1])
        st.append(sp)
        with self._lock:
            self._open += 1
        return sp

    def end_span(self, sp: Span, **tags) -> None:
        """Close `sp` and record it. Status becomes "error" when an
        exception is propagating through the enclosing finally, or when
        an explicit `error=...` tag is passed (for callers that caught
        the failure themselves). Idempotent."""
        if sp.ended:
            return
        sp.ended = True
        dur = max(time.monotonic() - sp.t0, 0.0)
        for k, v in tags.items():
            if v is not None:
                sp.tags[k] = v
        status = "ok"
        if sp.tags.get("error") is not None:
            status = "error"
        else:
            exc = sys.exc_info()[1]
            if exc is not None and exc is not sp.exc0:
                status = "error"
                sp.tags["error"] = type(exc).__name__
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        else:                      # defensive: out-of-order end
            for i in range(len(st) - 1, -1, -1):
                if st[i] is sp:
                    del st[i]
                    break
        self._record({
            "id": sp.id, "parent": sp.parent, "name": sp.name,
            "t0": sp.t0, "dur": dur, "tid": sp.tid,
            "rids": list(sp.rids), "status": status, "tags": sp.tags,
        }, opened=True)

    def add_span(self, name: str, t0: float, t1: float, rids=(),
                 tid: Optional[str] = None, **tags) -> None:
        """Record an already-measured interval as a closed span — the
        cross-thread stages (queue wait, the in-flight window) whose
        endpoints are monotonic stamps both sides already hold, so no
        span object ever crosses a thread hop open."""
        st = self._stack()
        parent = st[-1].id if st else None
        self._record({
            "id": next(self._ids), "parent": parent, "name": name,
            "t0": t0, "dur": max(t1 - t0, 0.0),
            "tid": tid or threading.current_thread().name,
            "rids": list(rids), "status": "ok",
            "tags": {k: v for k, v in tags.items() if v is not None},
        }, opened=False)

    def _record(self, d: dict, opened: bool) -> None:
        with self._lock:
            if opened:
                self._open -= 1
            self._stage_record_locked(d["name"], d["dur"] * 1e3)
            for rid in d["rids"]:
                acc = self._live.get(rid)
                if acc is not None:
                    acc["spans"].append(d)

    def _stage_record_locked(self, name: str, ms: float) -> None:
        h = self._stages.get(name)
        if h is None:
            h = self._stages[name] = [0, 0.0,
                                      [0] * (len(STAGE_BUCKETS_MS) + 1)]
        h[0] += 1
        h[1] += ms
        for i, ub in enumerate(STAGE_BUCKETS_MS):
            if ms <= ub:
                h[2][i] += 1
                break
        else:
            h[2][-1] += 1

    def stage_p99_ms(self, name: str) -> Optional[float]:
        """Histogram-estimated p99 of one stage's recorded durations:
        the upper bound of the bucket containing the 99th-percentile
        sample (None until the stage has samples; the overflow bucket
        reports twice the top bound — an honest 'at least'). The
        autoscaler's per-stage saturation signal (ISSUE 20): a
        queue.wait p99 climbing toward the SLO is the leading edge of
        overload, visible before sheds start."""
        with self._lock:
            h = self._stages.get(name)
            if h is None or not h[0]:
                return None
            target = 0.99 * h[0]
            acc = 0
            for i, n in enumerate(h[2]):
                acc += n
                if acc >= target:
                    return (float(STAGE_BUCKETS_MS[i])
                            if i < len(STAGE_BUCKETS_MS)
                            else STAGE_BUCKETS_MS[-1] * 2.0)
            return STAGE_BUCKETS_MS[-1] * 2.0

    # -- export ------------------------------------------------------------

    def traces(self) -> list:
        """Every retained trace (sampled ring + exemplars), oldest
        first within each class."""
        with self._lock:
            return list(self._ring) + list(self._exemplars)

    def breakdown(self, trace_id: str) -> Optional[dict]:
        """The finished stage breakdown for one trace id (bounded
        recent-window lookup — the Server-Timing source)."""
        with self._lock:
            d = self._recent.get(trace_id)
            return dict(d) if d is not None else None

    def server_timing(self, trace_id: str) -> Optional[str]:
        """RFC-compliant Server-Timing header value for a finished
        request: one `stage;dur=ms` entry per attributed stage plus the
        unattributed residue."""
        d = self.breakdown(trace_id)
        if d is None:
            return None
        parts = [f"{stage};dur={ms:.3f}"
                 for stage, ms in sorted(d["stages_ms"].items())]
        parts.append(f"residue;dur={d['residue_ms']:.3f}")
        return ", ".join(parts)

    def export_chrome(self, pid: int = 1,
                      process_name: str = "distributedmnist-serve"
                      ) -> dict:
        """Chrome trace-event JSON (loads directly in chrome://tracing
        and Perfetto): complete 'X' events on monotonic-microsecond
        timestamps, thread-name metadata per pipeline thread, one event
        per distinct span (batch spans shared across cohort traces are
        deduped by id). tid numbers are assigned per-export in
        first-encounter order, so a caller MERGING several tracers'
        exports into one file must give each a distinct `pid` —
        otherwise the second export's thread_name metadata relabels
        the first's tracks (bench.py's --trace --chaos merge passes
        pid per leg)."""
        traces = self.traces()
        events = [{"ph": "M", "pid": pid, "tid": 0,
                   "name": "process_name",
                   "args": {"name": process_name}}]
        tids: dict[str, int] = {}

        def tid_of(label: str) -> int:
            t = tids.get(label)
            if t is None:
                t = tids[label] = len(tids) + 1
                events.append({"ph": "M", "pid": pid, "tid": t,
                               "name": "thread_name",
                               "args": {"name": label}})
            return t

        seen: set = set()
        for tr in traces:
            for s in tr["spans"]:
                if s["id"] in seen:
                    continue
                seen.add(s["id"])
                events.append({
                    "name": s["name"],
                    "cat": "serve",
                    "ph": "X",
                    "ts": round(s["t0"] * 1e6, 1),
                    "dur": round(s["dur"] * 1e6, 1),
                    "pid": pid,
                    "tid": tid_of(s["tid"]),
                    "args": {"trace_ids": [f"{r:08x}" for r in s["rids"]],
                             "status": s["status"],
                             "parent": s["parent"], **s["tags"]},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def snapshot(self) -> dict:
        """Counters + the per-stage duration histograms (the /metrics
        `trace` block; the Prometheus exposition flattens `stages`)."""
        with self._lock:
            stages = {
                name: {
                    "count": h[0],
                    "sum_ms": round(h[1], 3),
                    "buckets": {**{f"{ub:g}": h[2][i]
                                   for i, ub in
                                   enumerate(STAGE_BUCKETS_MS)},
                                "+Inf": h[2][-1]},
                }
                for name, h in sorted(self._stages.items())}
            return {
                "slo_ms": self.slo_ms,
                "sample": self.sample,
                "capacity": self.capacity,
                "requests_started": self._started,
                "requests_finished": self._finished,
                "kept_sampled": self._kept_sampled,
                "kept_exemplars": self._kept_exemplar,
                "sampled_out": self._sampled_out,
                "aborted": self._aborted,
                "dropped_live": self._dropped_live,
                "live": len(self._live),
                "open_spans": self._open,
                "ring_traces": len(self._ring),
                "exemplar_traces": len(self._exemplars),
                "stages": stages,
            }


# The module-global active tracer. None (the default, every production
# process) keeps all woven hooks to one attribute read + None test —
# the serve/faults.py inertness idiom.
_active: Optional[Tracer] = None


def install(tracer: Tracer) -> Tracer:
    """Activate `tracer` process-wide. Refuses to stack: two tracers
    silently interleaved would make neither's retention trustworthy."""
    global _active
    if _active is not None:
        raise RuntimeError(
            "a Tracer is already installed; uninstall() it first")
    _active = tracer
    return tracer


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[Tracer]:
    return _active


def begin_span(name: str, rids=None, ctx=None, **tags) -> Optional[Span]:
    """The woven begin hook: None (and free) when no tracer is
    installed. Close with end_span in a try/finally (DML007)."""
    tr = _active
    if tr is None:
        return None
    return tr.begin_span(name, rids=rids, ctx=ctx, **tags)


def end_span(sp: Optional[Span], **tags) -> None:
    """Close a begin_span result; safe on None (tracer was off) and
    after uninstall (the span remembers its tracer)."""
    if sp is not None:
        sp.tracer.end_span(sp, **tags)


def add_span(name: str, t0: float, t1: float, rids=(),
             tid: Optional[str] = None, **tags) -> None:
    tr = _active
    if tr is not None:
        tr.add_span(name, t0, t1, rids=rids, tid=tid, **tags)


def current() -> Optional[tuple]:
    tr = _active
    if tr is None:
        return None
    return tr.current()
