"""Config-driven fault injection for the serving stack (ISSUE 5).

Untested failure handling is indistinguishable from none: the resilience
policies in serve/resilience.py (deadline shedding, poison-batch
bisection, circuit-breaker rollback) only earn trust if their triggering
faults can be produced deterministically, on demand, in tests and in
`bench.py serve --chaos`. This module is that trigger: named
**failpoints** woven through the serving layers —

    engine.dispatch    InferenceEngine.dispatch (per-call)
    engine.fetch       InferenceEngine.fetch (per-call; ctx: version)
    batch.dispatch     the batcher's dispatch site (ctx: rids — the
                       request-sticky point poison faults key on)
    router.shadow      the shadow duplicate dispatch in Router
    registry.restore   ModelRegistry.load_latest's checkpoint restore
    registry.warmup    ModelRegistry.add's engine build + warmup
    replica.dispatch   the fleet's per-replica dispatch (ctx: replica —
                       a rule with replica=r1 kills exactly that
                       replica, the chaos bench's replica-kill storm)
    replica.fetch      the fleet's per-replica fetch (ctx: replica,
                       version)

— each a single call to failpoint(name, **ctx). With no injector
installed that call is one module-global None check: the production hot
path pays nothing (the bench's chaos-off leg proves it stays within
noise of the pre-fault record).

An installed FaultInjector holds an ordered list of FaultRules parsed
from a compact spec string (config.serve_faults / --serve-faults /
the chaos bench's seeded schedule):

    point:key=val,key=val;point2:...

keys: p (probability, default 1), mode (call|request), error (message;
the rule raises InjectedFault), latency_ms (injected sleep before any
error), count (max fires), after (skip the first N matching
evaluations), plus any other key=val which becomes a ctx equality
filter (e.g. version=v1 fires only on that model version).

Two trigger modes:

- **call**: an independent seeded draw per evaluation — classic
  probabilistic chaos (flaky device, slow fetch).
- **request**: the draw is a deterministic hash of (seed, point, rid)
  per request id in ctx["rids"], and the rule fires iff the evaluation
  covers >= 1 "poison" request. The SAME request always poisons every
  dispatch that contains it — exactly the contract the batcher's
  bisection needs to isolate the culprit by splitting: cohort-mates
  re-dispatch clean, the culprit alone keeps failing.

Everything is seeded and lock-guarded, so a chaos schedule replays
bit-identically across runs and threads cannot corrupt rule state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Optional

from distributedmnist_tpu.analysis.locks import make_lock


# Every failpoint woven through the serving stack, by name. parse_spec
# refuses names outside this set: a typo'd point would otherwise
# install a schedule that silently injects nothing — and a chaos drill
# that injected nothing "proves" resilience it never exercised. Keep in
# lockstep with the failpoint() call sites (tests assert each name
# fires).
KNOWN_FAILPOINTS = frozenset((
    "engine.dispatch", "engine.fetch", "batch.dispatch",
    "router.shadow", "registry.restore", "registry.warmup",
    "registry.variant", "replica.dispatch", "replica.fetch"))


class InjectedFault(RuntimeError):
    """An error produced by a FaultRule. Carries the failpoint name so
    harnesses can attribute each failed request to the injection that
    caused it (500 semantics at the HTTP surface, like any engine
    error)."""

    status = 500

    def __init__(self, point: str, detail: str):
        super().__init__(f"injected fault at {point}: {detail}")
        self.point = point


@dataclasses.dataclass
class FaultRule:
    """One failpoint behavior. `match` entries are compared as strings
    against the failpoint's ctx (a rule with version=v1 only evaluates
    when ctx['version'] == 'v1')."""

    point: str
    probability: float = 1.0
    mode: str = "call"                # "call" | "request"
    error: Optional[str] = None       # None + latency_ms==0 -> error too
    latency_ms: float = 0.0
    count: Optional[int] = None       # max fires; None = unlimited
    after: int = 0                    # skip first N matching evaluations
    match: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.point:
            raise ValueError("fault rule needs a failpoint name")
        if not 0.0 < self.probability <= 1.0:
            raise ValueError(
                f"fault probability must be in (0, 1], "
                f"got {self.probability}")
        if self.mode not in ("call", "request"):
            raise ValueError(f"fault mode must be call|request, "
                             f"got {self.mode!r}")
        if self.latency_ms < 0:
            raise ValueError(
                f"latency_ms must be >= 0, got {self.latency_ms}")
        if self.count is not None and self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.after < 0:
            raise ValueError(f"after must be >= 0, got {self.after}")
        if self.error is None:
            if self.mode == "request":
                # a request-mode rule exists to FAIL poisoned requests
                self.error = "poison request"
            elif self.latency_ms == 0:
                # a rule with no error AND no latency would fire
                # invisibly — surely a mistake; default to an error.
                # Latency-only rules (latency_ms set, error unset)
                # stay non-raising slow-downs.
                self.error = "injected error"

    def describe(self) -> dict:
        return {"point": self.point, "p": self.probability,
                "mode": self.mode, "error": self.error,
                "latency_ms": self.latency_ms, "count": self.count,
                "after": self.after, "match": dict(self.match)}


def parse_spec(spec: str) -> list[FaultRule]:
    """`point:k=v,k=v;point:...` -> FaultRules. Raises ValueError with
    the offending fragment on any malformed piece (a chaos schedule
    must fail loudly at install, never silently inject nothing)."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, body = part.partition(":")
        point = point.strip()
        if point not in KNOWN_FAILPOINTS:
            raise ValueError(
                f"unknown failpoint {point!r} in {part!r}; known: "
                f"{sorted(KNOWN_FAILPOINTS)}")
        kw: dict = {"point": point, "match": {}}
        for item in (body.split(",") if body.strip() else []):
            key, sep, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if not sep or not key or not val:
                raise ValueError(
                    f"bad fault spec item {item!r} in {part!r} "
                    "(want key=value)")
            try:
                if key == "p":
                    kw["probability"] = float(val)
                elif key == "latency_ms":
                    kw["latency_ms"] = float(val)
                elif key in ("count", "after"):
                    kw[key] = int(val)
                elif key == "mode":
                    kw["mode"] = val
                elif key == "error":
                    kw["error"] = val
                else:
                    kw["match"][key] = val
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec value {item!r} in {part!r}: {e}"
                ) from None
        try:
            rules.append(FaultRule(**kw))
        except ValueError as e:
            raise ValueError(f"bad fault rule {part!r}: {e}") from None
    if not rules:
        raise ValueError(f"fault spec {spec!r} contains no rules")
    return rules


def _hash_draw(seed: int, point: str, rid: int) -> float:
    """Deterministic uniform [0,1) from (seed, point, rid): the same
    request gets the same verdict in every dispatch that contains it —
    the stickiness bisection needs (a plain RNG would re-roll on each
    retry and let the culprit slip through a split)."""
    h = hashlib.sha256(f"{seed}:{point}:{rid}".encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


class FaultInjector:
    """An installed set of FaultRules evaluated at every failpoint
    crossing. Thread-safe; all draws are seeded (call-mode rules from a
    per-rule RNG sequence, request-mode from a content hash), so a
    schedule replays deterministically."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        import random

        if not rules:
            raise ValueError("FaultInjector needs at least one rule")
        self.rules = list(rules)
        self.seed = seed
        self._lock = make_lock("faults.injector")
        self._rngs = [random.Random(f"{seed}:{i}")
                      for i in range(len(rules))]
        self._evals = [0] * len(rules)
        self._fires = [0] * len(rules)
        self._poisoned: set[int] = set()    # rids decided poison

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultInjector":
        return cls(parse_spec(spec), seed=seed)

    def fire(self, point: str, **ctx) -> None:
        """Evaluate every rule bound to `point`. May sleep
        (latency_ms) and/or raise InjectedFault. The latency sleep runs
        OUTSIDE the lock so a slow-fault rule on one thread cannot
        stall every other failpoint crossing."""
        delay = 0.0
        raising: Optional[str] = None
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.point != point:
                    continue
                if any(str(ctx.get(k)) != v
                       for k, v in rule.match.items()):
                    continue
                self._evals[i] += 1
                if self._evals[i] <= rule.after:
                    continue
                if rule.count is not None and self._fires[i] >= rule.count:
                    continue
                if rule.mode == "request":
                    rids = ctx.get("rids") or ()
                    poison = [r for r in rids
                              if _hash_draw(self.seed, point, r)
                              < rule.probability]
                    if not poison:
                        continue
                    self._poisoned.update(poison)
                else:
                    if self._rngs[i].random() >= rule.probability:
                        continue
                self._fires[i] += 1
                delay = max(delay, rule.latency_ms / 1e3)
                if rule.error is not None and raising is None:
                    raising = rule.error
        if delay:
            time.sleep(delay)
        if raising is not None:
            raise InjectedFault(point, raising)

    def poisoned(self) -> set:
        """Request ids this injector has (so far) decided are poison —
        the chaos bench's ground truth for 'every culprit was isolated,
        no cohort-mate was misblamed'."""
        with self._lock:
            return set(self._poisoned)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [
                    {**r.describe(), "evaluations": self._evals[i],
                     "fires": self._fires[i]}
                    for i, r in enumerate(self.rules)],
                "poisoned_requests": len(self._poisoned),
            }


# The module-global active injector. None (the default, and the state
# every production process runs in) makes failpoint() a single
# attribute read + None test — the woven failpoints are free when chaos
# is off.
_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    """Activate `injector` process-wide. Refuses to stack: two
    schedules silently combined would make neither reproducible."""
    global _active
    if _active is not None:
        raise RuntimeError(
            "a FaultInjector is already installed; uninstall() it first")
    _active = injector
    return injector


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[FaultInjector]:
    return _active


def failpoint(point: str, **ctx) -> None:
    """The woven hook: evaluate the active injector's rules at `point`.
    Inert (one None check) when nothing is installed."""
    inj = _active
    if inj is not None:
        inj.fire(point, **ctx)
