"""Checkpoint-backed model lifecycle: versioned param sets, pre-warmed
engines, atomic promotion.

PRs 1-2 made serving fast but frozen: one engine, one param set, loaded
at process start. Rolling in a newly trained checkpoint meant killing the
server. This module is the model-abstraction layer above the compute
engine (the Clipper decomposition): a **ModelRegistry** that

1. **loads** versioned param sets — params-only restore from checkpoint
   directories (checkpoint.restore_latest_params; no optimizer slots
   read), or params handed in directly (fresh-init bench/gate paths);
2. **pre-warms** every bucket of the new version's jitted forward OFF the
   hot path, then proves warmth by re-running warmup and asserting zero
   compile events (Clockwork's rule: a model never takes live traffic
   until its programs are fully compiled — one cold bucket after a swap
   would poison tail latency for every later request that lands in it);
3. **promotes** a warmed version by atomically re-pointing the Router's
   live target while the dispatch thread keeps running — in-flight
   batches finish on the engine their handle captured, the next batch
   runs the new version, and no request ever observes a mixed-version
   result;
4. keeps a bounded set of warmed versions resident (rollback = promote a
   previous version; eviction drops the oldest routeless version so HBM
   isn't a leak of every checkpoint ever loaded).

All versions in one registry share one EngineFactory — same model, mesh,
dtype, bucket ladder — so a swap can never change compile geometry, which
is what keeps recompiles_after_warmup == 0 true ACROSS swaps, not just
within one engine's steady state.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Any, Optional, Sequence

import numpy as np

from distributedmnist_tpu.analysis.locks import make_lock, make_rlock
from distributedmnist_tpu.serve.engine import InferenceEngine, make_buckets
from distributedmnist_tpu.serve.faults import failpoint
from distributedmnist_tpu.serve.router import Router

log = logging.getLogger("distributedmnist_tpu")

# Version lifecycle: warming -> ready -> live -> ready (demoted, can be
# re-promoted as a rollback) -> evicted. "failed" is terminal (warmup
# did not reach the compiled-everywhere bar).
STATES = ("warming", "ready", "live", "failed")

# The serve-side accuracy-parity gate (ISSUE 7): per-dtype thresholds a
# low-precision variant must clear against the float32 reference on the
# held-out batch before it may EVER take traffic — (min argmax
# agreement, max relative logit diff; utils/numerics.parity_check).
# Values and their measured headroom are documented in PARITY.md
# ("Serving parity gate"): bf16 carries ~0.4% relative mantissa error,
# int8 per-channel weight quantization ~2-4% worst-case relative logit
# error on this repo's models — the thresholds sit ~4-10x above the
# honest error and far below a broken variant's (wrong scales land at
# relative error O(1)).
PARITY_GATES = {"bfloat16": (0.995, 0.05), "int8": (0.995, 0.15),
                # The whole-net fused-inference megakernel (ISSUE 14):
                # float32 numerics end to end, so the only honest error
                # sources are the /255 fold into layer-1 weights and
                # f32 reassociation inside the fused matmul chain —
                # relative logit error O(1e-6) measured on both fresh
                # and trained MLPs. The tight 0.01 relative bar (5-15x
                # tighter than the low-precision gates, documented in
                # PARITY.md) means a megakernel that drifts at all
                # reads as broken, which for a pure-kernel variant it
                # is.
                "megakernel": (0.995, 0.01)}

# Rows in the held-out parity batch (capped at the engine's max_batch):
# deterministic calibrated-synthetic test images, the same distribution
# the smoke gate's accuracy floor runs on.
PARITY_ROWS = 128
PARITY_SEED = 709


@dataclasses.dataclass
class VariantInfo:
    """One low-precision engine set of a version (ISSUE 7): the same
    params served through the serve/quantize.py fast path in
    `infer_dtype`. Lifecycle mirrors the version's (warming -> ready,
    or terminal failed) with one extra bar: the accuracy-parity gate —
    a variant that compiles everywhere but disagrees with the f32
    reference is REFUSED, its last_error says why, and promote() will
    never route it."""

    infer_dtype: str
    state: str = "warming"
    engines: list = dataclasses.field(default_factory=list)
    engine: Any = None             # replica 0's engine (None until warm)
    warmup_compile_events: int = 0
    warmup_s: float = 0.0
    loaded_at: float = 0.0
    parity: Optional[dict] = None  # utils.numerics.parity_check record
    last_error: Optional[str] = None
    last_error_at: Optional[float] = None

    def record_error(self, error: str) -> None:
        self.last_error = error
        # lint: allow[DML004] wall-clock incident stamp for operators, never elapsed math
        self.last_error_at = time.time()

    def describe(self) -> dict:
        return {
            "infer_dtype": self.infer_dtype,
            "state": self.state,
            "warmup_compile_events": self.warmup_compile_events,
            "warmup_s": round(self.warmup_s, 3),
            "parity": self.parity,
            "last_error": self.last_error,
            "last_error_at": (round(self.last_error_at, 3)
                              if self.last_error_at is not None else None),
            "bucket_cost_ms": ({
                str(b): round(c * 1e3, 3)
                for b, c in sorted(self.engine.bucket_costs().items())}
                if self.engine is not None else None),
            "replica_engines": len(self.engines),
        }


@dataclasses.dataclass
class ModelVersion:
    version: str
    engine: Any                    # replica 0's engine (None until warm)
    state: str
    source: str                    # "checkpoint <dir>" | "fresh-init" | ...
    # One warmed engine per fleet replica (ISSUE 6), [engine] on a
    # single-replica registry: promote/shadow/canary fan the whole list
    # out so every replica rolls together. `engine` stays the first
    # entry for the single-replica surface tests and describe() use.
    engines: list = dataclasses.field(default_factory=list)
    step: Optional[int] = None     # checkpoint step, when from disk
    warmup_compile_events: int = 0
    warmup_s: float = 0.0
    loaded_at: float = 0.0         # wall clock, display only
    # Monotonic load sequence stamp: "newest healthy resident" ordering
    # (rollback's fallback pick) must survive a wall-clock step — a
    # backwards NTP jump re-ordering loaded_at could roll back to the
    # WRONG version (ISSUE 8 lint DML004 finding, fixed).
    loaded_mono: float = 0.0
    # The last failure this version suffered (restore/warmup exception
    # string, or the circuit-breaker trip reason that demoted it) plus
    # its wall-clock timestamp — surfaced in GET /models so an operator
    # sees WHY a version is failed/rolled-back instead of grepping logs
    # (ISSUE 5 satellite). None = healthy; auto-rollback only promotes
    # residents with last_error None.
    last_error: Optional[str] = None
    last_error_at: Optional[float] = None
    # Low-precision engine sets of THIS version's params, keyed by
    # infer_dtype (ISSUE 7). The float32 base is `engines` above, not an
    # entry here; a variant only appears after add_variant() warmed it
    # and it either cleared or failed the parity gate.
    variants: dict = dataclasses.field(default_factory=dict)
    # Calibrated confidence cascade over this version (ISSUE 17):
    # serve/cascade.CascadeState once enable_cascade()'s end-to-end
    # cascade-accuracy gate passed; None otherwise (the CascadeFront
    # degrades every class to the plain live route).
    cascade: Any = None

    def record_error(self, error: str) -> None:
        self.last_error = error
        # lint: allow[DML004] wall-clock incident stamp for operators, never elapsed math
        self.last_error_at = time.time()

    def describe(self) -> dict:
        return {
            "version": self.version,
            "state": self.state,
            "source": self.source,
            "step": self.step,
            "warmup_compile_events": self.warmup_compile_events,
            "warmup_s": round(self.warmup_s, 3),
            "loaded_at": round(self.loaded_at, 3),
            "last_error": self.last_error,
            "last_error_at": (round(self.last_error_at, 3)
                              if self.last_error_at is not None else None),
            # The warmup-measured per-bucket dispatch cost this
            # version's batch former plans with (GET /models shows an
            # operator what the scheduler believes about each program).
            "bucket_cost_ms": ({
                str(b): round(c * 1e3, 3)
                for b, c in sorted(self.engine.bucket_costs().items())}
                if self.engine is not None else None),
            # one warmed engine per fleet replica; 1 on a single-router
            # registry, 0 while warming/failed
            "replica_engines": len(self.engines),
            # the base engines' serving precision (the parity oracle)
            "infer_dtype": (self.engine.infer_dtype
                            if self.engine is not None else None),
            # low-precision variants of this version: state, parity
            # verdict, per-dtype cost table, refusal reason (ISSUE 7)
            "variants": {dt: v.describe()
                         for dt, v in sorted(self.variants.items())},
            # calibrated cascade state: cheap dtype, the one threshold,
            # and the cascade-accuracy gate's record (ISSUE 17)
            "cascade": (self.cascade.describe()
                        if self.cascade is not None else None),
        }


class EngineFactory:
    """Builds shape-identical InferenceEngines, one per (model version,
    fleet replica).

    Owns the shared geometry (model, per-replica meshes, dtype, bucket
    ladder) so every version compiles the same set of programs, and
    exposes the abstract params tree (shapes/dtypes/replicated sharding)
    the params-only checkpoint restore needs — computed via eval_shape,
    no device work.

    With `replicas` > 1 (ISSUE 6) the mesh's devices are cut into equal
    slices, one per replica, when they divide evenly — each replica's
    engines then run on disjoint chips (a real fault/perf isolation
    domain). Hosts without enough devices (the 1-chip CPU bench host)
    fall back to N LOGICAL replicas sharing the full mesh: separate
    engines, separate staging pools, separate jitted programs — the
    full dispatch/failover machinery exercised, minus the physical
    isolation. `n_chips` / `mesh` / `buckets` are PER-REPLICA (the
    bucket ladder must shard over one replica's data-parallel width);
    `total_chips` is the whole fleet's denominator."""

    def __init__(self, model, mesh, dtype=None, max_batch: int = 512,
                 buckets: Optional[Sequence[int]] = None,
                 replicas: int = 1, fused: str = "auto"):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.model = model
        self.replicas = replicas
        # The fused-kernel mode every engine of this factory resolves
        # against its mesh's platform (cfg.fused_kernels): the Pallas
        # hot-op route for the quantized fast path on TPU, XLA on CPU.
        self.fused = fused
        devices = list(mesh.devices.flat)
        if replicas > 1 and len(devices) >= replicas \
                and len(devices) % replicas == 0:
            from distributedmnist_tpu.parallel import make_mesh

            k = len(devices) // replicas
            self.meshes = [make_mesh(devices[i * k:(i + 1) * k])
                           for i in range(replicas)]
        else:
            self.meshes = [mesh] * replicas
        self.mesh = self.meshes[0]
        self.dtype = dtype
        self.max_batch = max_batch
        self.n_chips = int(np.prod(self.mesh.devices.shape))
        self.total_chips = len({d for m in self.meshes
                                for d in m.devices.flat})
        self.platform = mesh.devices.flat[0].platform
        self.buckets = (tuple(sorted(set(buckets))) if buckets
                        else make_buckets(max_batch, self.n_chips))

    def make_router(self, metrics=None, seed: int = 0,
                    replica: Optional[str] = None) -> Router:
        return Router(self.max_batch, self.buckets, self.platform,
                      n_chips=self.n_chips, metrics=metrics, seed=seed,
                      replica=replica)

    def make_fleet(self, metrics=None, seed: int = 0,
                   per_replica_inflight: Optional[int] = None,
                   hedge: bool = False):
        """The N-replica dispatcher (serve/fleet.py): one Router per
        replica, each labelled rN and seeded distinctly so canary/
        shadow sampling never locksteps across replicas."""
        from distributedmnist_tpu.serve.fleet import ReplicaSet

        routers = [self.make_router(metrics=metrics, seed=seed + i,
                                    replica=f"r{i}")
                   for i in range(self.replicas)]
        return ReplicaSet(routers, metrics=metrics,
                          per_replica_inflight=per_replica_inflight,
                          hedge=hedge)

    def make_engine(self, params, version: str, replica: int = 0,
                    infer_dtype: str = "float32") -> InferenceEngine:
        return InferenceEngine(self.model, params, self.meshes[replica],
                               dtype=self.dtype, max_batch=self.max_batch,
                               buckets=self.buckets, version=version,
                               infer_dtype=infer_dtype,
                               fused_mode=self.fused)

    def init_params(self, seed: int = 0):
        """Fresh-init params (load harnesses and gates measure plumbing
        and throughput, not accuracy), replicated over the mesh."""
        import jax
        import jax.numpy as jnp

        from distributedmnist_tpu.parallel import replicated

        params = self.model.init(jax.random.PRNGKey(seed),
                                 jnp.zeros((1, 28, 28, 1)))["params"]
        # lint: allow[DML012] build-time param placement on the admin path, never per-request
        return jax.device_put(params, replicated(self.mesh))

    def abstract_params(self):
        """Params-shaped ShapeDtypeStruct tree with replicated sharding —
        the restore target for checkpoint.restore_latest_params."""
        import jax
        import jax.numpy as jnp

        from distributedmnist_tpu.parallel import replicated

        shapes = jax.eval_shape(
            lambda k: self.model.init(k, jnp.zeros((1, 28, 28, 1)))
            ["params"], jax.random.PRNGKey(0))
        sharding = replicated(self.mesh)
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=sharding), shapes)


class ModelRegistry:
    """Versioned, pre-warmed model store feeding one Router.

    Two locks with distinct jobs: `_admin` (RLock) serializes the slow
    mutating operations (add/load/promote/set_shadow/set_canary — they
    run on admin/HTTP/SIGHUP threads, never the dispatch thread, so
    warmup is always off the hot path); `_state` (Lock) guards only the
    version table itself and is held for dict operations, never across
    a restore or a warmup — so /healthz and GET /models (describe())
    answer instantly even while a multi-second candidate warmup is in
    flight. The dispatch thread waits on neither: it only ever crosses
    the Router's pointer lock (nanoseconds, not a compile)."""

    def __init__(self, factory: EngineFactory, router: Router,
                 checkpoint_dir: Optional[str] = None,
                 max_versions: int = 4):
        if max_versions < 2:
            raise ValueError(
                f"max_versions must be >= 2 (live + one candidate), "
                f"got {max_versions}")
        from distributedmnist_tpu.utils import CompileCounter

        self.factory = factory
        self.router = router
        self.checkpoint_dir = checkpoint_dir
        self.max_versions = max_versions
        # Fleet-aware (ISSUE 6): a ReplicaSet router means every
        # version warms ONE ENGINE PER REPLICA and every routing
        # mutation fans the whole list out — a roll moves the entire
        # fleet, never a subset. A plain Router keeps the 1-engine
        # surface byte-for-byte.
        self.n_replicas = getattr(router, "n_replicas", 1)
        self._versions: dict[str, ModelVersion] = {}   # insertion-ordered
        # blocking_ok: the admin lock serializes multi-second restores
        # and warmups BY DESIGN (they run on admin/SIGHUP threads, never
        # the dispatch path) — the sanitizer's blocking-under-lock check
        # must not flag what the two-lock split exists to permit. _state
        # stays hot-path strict: holding it across anything slow is
        # exactly the PR 3 bug the split fixed.
        self._admin = make_rlock("registry.admin", blocking_ok=True)
        self._state = make_lock("registry.state")
        self._compiles = CompileCounter.instance()
        self._auto_id = 0
        # The prediction cache to invalidate on every live-route change
        # (ISSUE 10): promote, rollback and dtype activation all funnel
        # through _route_set("live", ...), so one hook site covers the
        # whole surface. None = no cache installed (every pre-ISSUE-10
        # caller).
        self._cache = None
        # Lifecycle events an operator must be able to reconstruct
        # AFTER the fact (ISSUE 5): circuit-breaker rollbacks above all.
        # Bounded; surfaced by events(), describe() and /healthz.
        self._events: deque = deque(maxlen=64)

    # -- loading -----------------------------------------------------------

    def add_fresh(self, version: Optional[str] = None,
                  seed: int = 0) -> ModelVersion:
        """Register + pre-warm a fresh-initialized param set — the
        bootstrap fallback's param source behind the full add() warmup
        gate, as an admin surface (ISSUE 19: POST /models/load
        {"fresh": ...}). A gateway bench stages a promotable second
        version on EVERY worker of a fleet this way: same seed, same
        params, no shared trained checkpoint required."""
        return self.add(self.factory.init_params(seed), version=version,
                        source="fresh-init")

    def add(self, params, version: Optional[str] = None,
            source: str = "direct", step: Optional[int] = None
            ) -> ModelVersion:
        """Register + pre-warm a param set. Returns the ModelVersion in
        state 'ready' (promotable). Raises if the version name is taken,
        if the registry is full of route-holding versions (BEFORE any
        warmup work is spent), or if warmup cannot reach the
        compiled-everywhere bar."""
        with self._admin:
            with self._state:
                if version is None:
                    self._auto_id += 1
                    version = f"v{self._auto_id}"
                if version in self._versions:
                    raise ValueError(f"version {version!r} already loaded")
                # Capacity check up front: if every resident version
                # holds a routing role, eviction could free nothing and
                # the newcomer itself would be the only evictable entry
                # — refuse NOW rather than warm an engine just to drop
                # it (or silently exceed the HBM cap).
                in_route = self.router.versions_in_route()
                evictable = [n for n, v in self._versions.items()
                             if v.state == "failed"
                             or (v.state == "ready" and n not in in_route)]
                if len(self._versions) >= self.max_versions \
                        and not evictable:
                    raise RuntimeError(
                        f"registry full: {len(self._versions)} resident "
                        "versions all hold routing roles (live/shadow/"
                        "canary); clear a candidate or raise "
                        "serve_max_versions")
                mv = ModelVersion(version=version, engine=None,
                                  state="warming", source=source,
                                  step=step,
                                  # lint: allow[DML004] wall display stamp; ordering uses loaded_mono
                                  loaded_at=time.time(),
                                  loaded_mono=time.monotonic())
                self._versions[version] = mv
            # Warmup runs OUTSIDE the state lock (it is seconds of XLA
            # compile): /healthz and GET /models stay answerable — they
            # see this version honestly in state 'warming'. The admin
            # lock still serializes concurrent loads.
            try:
                t0 = time.perf_counter()
                # Fault-injection seam (serve/faults.py): an injected
                # warmup failure exercises the same failed-version
                # bookkeeping a real compile/OOM failure would.
                failpoint("registry.warmup", version=version)
                # One engine PER REPLICA (a single engine on a plain
                # Router), each proved warm individually: a version is
                # promotable only when EVERY replica can serve it with
                # zero residual compiles — promote fans out fleet-wide,
                # so one cold replica would poison the fleet's tail.
                engines = []
                compile_events = 0
                for i in range(self.n_replicas):
                    engine = self.factory.make_engine(params, version,
                                                      replica=i)
                    compile_events += engine.warmup()
                    # Clockwork bar: prove EVERY bucket is compiled by
                    # re-running warmup — a pure jit-cache pass costs
                    # zero compile events or this version must not take
                    # traffic.
                    residual = engine.warmup()
                    if residual:
                        raise RuntimeError(
                            f"version {version!r} (replica {i}) still "
                            f"compiled {residual} time(s) on the "
                            "verification warmup pass — refusing to "
                            "mark it promotable")
                    engines.append(engine)
                mv.warmup_compile_events = compile_events
                mv.engines = engines
                mv.engine = engines[0]
                mv.warmup_s = time.perf_counter() - t0
                mv.state = "ready"
            except Exception as e:
                mv.state = "failed"
                mv.engine = None     # don't pin a half-warm engine's HBM
                mv.engines = []
                # Surfaced per-version in GET /models, not just logged:
                # a failed load's WHY must outlive the admin request
                # that triggered it (ISSUE 5 satellite).
                mv.record_error(f"warmup: {type(e).__name__}: {e}")
                raise
            with self._state:
                self._evict_locked(protect={version})
            log.info(
                "registry: %s ready (%s, %d compile events, %.2fs warm)",
                version, source, mv.warmup_compile_events, mv.warmup_s)
            return mv

    def load_latest(self, directory: Optional[str] = None,
                    version: Optional[str] = None) -> ModelVersion:
        """Load + pre-warm the latest committed checkpoint of `directory`
        (default: the registry's checkpoint_dir) via the params-only
        restore. Idempotent per checkpoint step: re-loading an already
        resident step returns the existing version instead of burning a
        duplicate engine's HBM (SIGHUP can fire repeatedly)."""
        from distributedmnist_tpu.checkpoint import restore_latest_params

        directory = directory or self.checkpoint_dir
        if not directory:
            raise ValueError(
                "no checkpoint directory: pass one or construct the "
                "registry with checkpoint_dir")
        from distributedmnist_tpu.checkpoint import committed_steps

        with self._admin:
            # Residency check BEFORE the restore: a periodic SIGHUP with
            # no new checkpoint must cost one listdir, not a full
            # params read + device placement that is then discarded.
            steps = committed_steps(directory)
            if not steps:
                raise FileNotFoundError(
                    f"no committed checkpoint in {directory!r}")
            step = steps[-1]
            if version is None:
                version = f"step-{step}"
            with self._state:
                existing = self._versions.get(version)
                if existing is not None and existing.state != "failed":
                    if existing.step == step:
                        log.info("registry: %s already resident "
                                 "(state %s)", version, existing.state)
                        return existing
                    # An explicit name pointing at OLDER params than the
                    # latest commit must not masquerade as a fresh load.
                    raise ValueError(
                        f"version {version!r} already holds step "
                        f"{existing.step}; latest committed step is "
                        f"{step} — pick a new version name (or omit it "
                        "for step-derived names)")
                if existing is not None:      # failed: allow a retry
                    del self._versions[version]
            # Pin the step decided above: a checkpoint committing
            # between the listing and the restore must not smuggle
            # newer params in under the older step's version name.
            try:
                # Fault-injection seam (serve/faults.py): an injected
                # restore failure drives the same failed-version path a
                # corrupt/mismatched checkpoint would.
                failpoint("registry.restore", directory=directory,
                          step=step)
                params, step = restore_latest_params(
                    directory, self.factory.abstract_params(), step=step)
            except Exception as e:
                # The restore died before add() could own the version:
                # register a failed entry anyway so GET /models surfaces
                # WHAT failed and WHY, instead of the error living only
                # in one admin response / log line (ISSUE 5 satellite).
                # A later retry of the same name is allowed (the
                # failed-entry check above deletes it).
                mv = ModelVersion(version=version, engine=None,
                                  state="failed",
                                  source=f"checkpoint {directory}",
                                  step=step,
                                  # lint: allow[DML004] wall display stamp; ordering uses loaded_mono
                                  loaded_at=time.time(),
                                  loaded_mono=time.monotonic())
                mv.record_error(f"restore: {type(e).__name__}: {e}")
                with self._state:
                    self._versions.setdefault(version, mv)
                raise
            return self.add(params, version=version,
                            source=f"checkpoint {directory}", step=step)

    def bootstrap(self, seed: int = 0) -> ModelVersion:
        """The process-start path: latest checkpoint if the registry's
        checkpoint_dir holds one, fresh-init params otherwise — then
        promote, so exactly one call takes a cold process to a live,
        fully-warmed model. If some OTHER version went live while this
        one warmed (an admin load+promote or SIGHUP raced the boot
        thread), the operator's newer choice wins: bootstrap must never
        silently revert live traffic to its own (possibly fresh-init)
        params."""
        from distributedmnist_tpu.checkpoint import committed_steps

        if self.checkpoint_dir and committed_steps(self.checkpoint_dir):
            mv = self.load_latest()
        else:
            mv = self.add(self.factory.init_params(seed),
                          source="fresh-init")
        with self._admin:
            live = self.live_version()
            if live is None or live == mv.version:
                self.promote(mv.version)
            else:
                log.info(
                    "bootstrap: %s went live during warmup; leaving it "
                    "(%s stays ready)", live, mv.version)
        return mv

    # -- dtype variants (ISSUE 7) ------------------------------------------

    def _parity_batch(self) -> np.ndarray:
        """The held-out gate batch: deterministic calibrated-synthetic
        test images (the smoke gate's distribution), capped at the
        engine geometry's max_batch so one infer() covers it."""
        from distributedmnist_tpu.data import synthetic_mnist

        rows = min(PARITY_ROWS, self.factory.max_batch)
        data = synthetic_mnist(seed=PARITY_SEED, train_n=16, test_n=rows)
        return np.asarray(data["test_x"][:rows])

    def add_variant(self, version: str, infer_dtype: str,
                    min_agreement: Optional[float] = None,
                    max_rel_diff: Optional[float] = None) -> VariantInfo:
        """Warm a low-precision engine set for `version` and gate it.

        Same bar as a new version (every replica compiled everywhere,
        zero residual compile events on the verification pass) PLUS the
        accuracy-parity gate: the held-out batch runs through the f32
        reference engine and the candidate, and the variant is REFUSED —
        state 'failed', last_error naming the failing threshold, never
        promotable — unless argmax agreement and the relative logit diff
        clear the per-dtype thresholds (PARITY_GATES / PARITY.md).
        Idempotent per (version, dtype): an already-ready variant
        returns as-is — unless caller-supplied thresholds are passed,
        in which case its existing engines are RE-GATED at that bar
        (never silently judged at the looser default); a failed one may
        be retried."""
        from distributedmnist_tpu.utils import parity_check

        if infer_dtype not in PARITY_GATES:
            raise ValueError(
                f"unknown variant dtype {infer_dtype!r} (expected one "
                f"of {sorted(PARITY_GATES)}; float32 is the base)")
        gate_agree, gate_rel = PARITY_GATES[infer_dtype]
        if min_agreement is not None:
            gate_agree = min_agreement
        if max_rel_diff is not None:
            gate_rel = max_rel_diff
        with self._admin:
            custom_gate = (min_agreement is not None
                           or max_rel_diff is not None)
            with self._state:
                mv = self._get(version)
                if mv.state not in ("ready", "live"):
                    raise RuntimeError(
                        f"version {version!r} is {mv.state!r}; variants "
                        "hang off a warmed version")
                existing = mv.variants.get(infer_dtype)
                if existing is not None and existing.state == "ready" \
                        and not custom_gate:
                    return existing
            if existing is not None and existing.state == "ready":
                # Custom thresholds against an already-warm variant:
                # RE-GATE the existing engines (no rebuild — they may
                # be routed) instead of returning a verdict that was
                # judged at the default bar. A failure records + bars
                # future promotes exactly like a build-time refusal.
                x = self._parity_batch()
                # lint: allow[DML015] admin-path parity-gate measurement, never the request path
                ref = mv.engines[0].infer(x)
                # lint: allow[DML015] admin-path parity-gate measurement, never the request path
                cand = existing.engines[0].infer(x)
                parity = parity_check(ref, cand,
                                      min_agreement=gate_agree,
                                      max_rel_diff=gate_rel)
                existing.parity = parity
                if not parity["passed"]:
                    existing.state = "failed"
                    existing.record_error(
                        f"re-gate REFUSED {infer_dtype!r} variant of "
                        f"{version!r}: {parity['why']}")
                    # A refused variant must stop serving NOW, not at
                    # the next operator promote: if it is the live
                    # target, demote to the version's f32 base (event-
                    # logged like a rollback — a precision demotion is
                    # an incident an operator reconstructs after the
                    # fact).
                    live_dt = getattr(self.router, "live_infer_dtype",
                                      lambda: None)()
                    if (self.router.live_version() == version
                            and live_dt == infer_dtype):
                        self._route_set("live", mv)
                        with self._state:
                            self._events.append({
                                "event": "variant_demoted",
                                "version": version,
                                "infer_dtype": infer_dtype,
                                "to": "float32",
                                "reason": existing.last_error,
                                # lint: allow[DML004] wall-clock event stamp for operators
                                "at": round(time.time(), 3)})
                        log.warning(
                            "registry: live variant %s of %s demoted "
                            "to float32 (%s)", infer_dtype, version,
                            parity["why"])
                    raise RuntimeError(existing.last_error)
                return existing
            with self._state:
                vi = VariantInfo(infer_dtype=infer_dtype,
                                 # lint: allow[DML004] wall display stamp
                                 loaded_at=time.time())
                mv.variants[infer_dtype] = vi
            # Warmup + gate run OUTSIDE the state lock, same as add():
            # /healthz and GET /models answer during the multi-second
            # variant warm (it honestly shows state 'warming').
            try:
                t0 = time.perf_counter()
                # Fault-injection seam: an injected variant failure
                # drives the same refused-variant bookkeeping a real
                # compile/parity failure would.
                failpoint("registry.variant", version=version,
                          dtype=infer_dtype)
                engines = []
                compile_events = 0
                params = mv.engines[0].params   # the f32 base tree
                for i in range(self.n_replicas):
                    engine = self.factory.make_engine(
                        params, version, replica=i,
                        infer_dtype=infer_dtype)
                    compile_events += engine.warmup()
                    residual = engine.warmup()
                    if residual:
                        raise RuntimeError(
                            f"variant {infer_dtype!r} of {version!r} "
                            f"(replica {i}) still compiled {residual} "
                            "time(s) on the verification warmup pass — "
                            "refusing to mark it promotable")
                    engines.append(engine)
                # The accuracy-parity gate: f32 reference vs candidate
                # on the held-out batch. A refusal is terminal for this
                # build — the variant must never be silently served.
                x = self._parity_batch()
                # lint: allow[DML015] admin-path parity-gate measurement, never the request path
                ref = mv.engines[0].infer(x)
                # lint: allow[DML015] admin-path parity-gate measurement, never the request path
                cand = engines[0].infer(x)
                parity = parity_check(ref, cand,
                                      min_agreement=gate_agree,
                                      max_rel_diff=gate_rel)
                vi.parity = parity
                if not parity["passed"]:
                    raise RuntimeError(
                        f"parity gate REFUSED {infer_dtype!r} variant "
                        f"of {version!r}: {parity['why']}")
                vi.engines = engines
                vi.engine = engines[0]
                vi.warmup_compile_events = compile_events
                vi.warmup_s = time.perf_counter() - t0
                vi.state = "ready"
            except Exception as e:
                vi.state = "failed"
                vi.engines = []
                vi.engine = None     # don't pin a refused engine's HBM
                vi.record_error(f"{type(e).__name__}: {e}")
                raise
            log.info(
                "registry: %s variant %s ready (%d compile events, "
                "%.2fs warm; parity agree=%s rel_diff=%s)", version,
                infer_dtype, vi.warmup_compile_events, vi.warmup_s,
                vi.parity["argmax_agreement"],
                vi.parity["max_rel_logit_diff"])
            return vi

    def cheapest_variant(self, version: str) -> str:
        """The auto-pick rule: among the f32 base and this version's
        parity-PASSING ready variants, the dtype whose warmup-measured
        cost table prices the bucket ladder cheapest (sum over rungs —
        every engine shares one ladder, so the sums are comparable).
        Variants that failed the gate never compete."""
        with self._state:
            mv = self._get(version)
            candidates = {"float32": mv.engines[0]}
            for dt, vi in mv.variants.items():
                if vi.state == "ready" and vi.engine is not None:
                    candidates[dt] = vi.engine

        def price(engine) -> float:
            costs = engine.bucket_costs()
            return sum(costs.values()) if costs else float("inf")

        return min(candidates, key=lambda dt: price(candidates[dt]))

    def activate_infer_dtype(self, version: str, choice: str) -> str:
        """serve.py's --serve-infer-dtype driver: warm + gate the
        requested variant(s) of `version`, then promote the pick.
        choice 'auto' tries every gated dtype this model SUPPORTS
        (serve/quantize.variant_supported — the megakernel exists for
        the MLP only, and auto must skip an impossible variant rather
        than record it as a refusal) and promotes the cheapest
        parity-passing one (possibly staying on float32); an explicit
        dtype raises if its variant is refused — the caller keeps
        serving f32 and the refusal is visible in GET /models. Returns
        the dtype now live."""
        from distributedmnist_tpu.serve.quantize import variant_supported

        if choice == "auto":
            targets = [dt for dt in PARITY_GATES
                       if variant_supported(self.factory.model, dt)]
        else:
            targets = [choice]
        errors = {}
        for dt in targets:
            try:
                self.add_variant(version, dt)
            except Exception as e:
                errors[dt] = e
                log.warning("variant %s of %s refused: %s", dt, version,
                            e)
        if choice == "auto":
            pick = self.cheapest_variant(version)
        else:
            if choice in errors:
                raise errors[choice]
            pick = choice
        self.promote(version, infer_dtype=pick)
        return pick

    # -- confidence cascade (ISSUE 17) -------------------------------------

    def _cascade_gate(self, mv: ModelVersion, vi: VariantInfo,
                      threshold: Optional[float] = None,
                      max_escalation: float = 0.5) -> dict:
        """The END-TO-END cascade-accuracy gate: run the held-out parity
        batch through the f32 reference and the cheap variant, then
        calibrate (or, with `threshold`, validate) the escalation
        threshold so the COMPOSED answer matches f32 within the same
        agreement bar a single variant must clear (PARITY.md)."""
        from distributedmnist_tpu.serve import cascade as cascade_mod

        x = self._parity_batch()
        # lint: allow[DML015] admin-path cascade parity-gate calibration, never the request path
        ref = mv.engines[0].infer(x)
        # lint: allow[DML015] admin-path cascade parity-gate calibration, never the request path
        cheap = vi.engines[0].infer(x)
        return cascade_mod.calibrate(
            np.asarray(ref), np.asarray(cheap),
            min_agreement=PARITY_GATES[vi.infer_dtype][0],
            threshold=threshold, max_escalation=max_escalation)

    def _refresh_live_routes(self, mv: ModelVersion) -> None:
        """Re-point the live route at `mv` (same engines, current
        alternates) and flush the prediction cache: called after a
        cascade state change so pinned routes and composed cache
        entries never serve stale calibration."""
        live_dt = getattr(self.router, "live_infer_dtype",
                          lambda: None)()
        engines = None
        if live_dt not in (None, "float32"):
            lvi = mv.variants.get(live_dt)
            if lvi is not None and lvi.engines:
                engines = lvi.engines
        self._route_set("live", mv, engines=engines)

    def enable_cascade(self, version: Optional[str] = None,
                       cheap_dtype: str = "auto",
                       threshold: Optional[float] = None,
                       max_escalation: float = 0.5):
        """Calibrate + gate a confidence cascade on `version` (default:
        the live one). `cheap_dtype` 'auto' picks the cheapest
        ALREADY-ready non-f32 variant by warmup-measured bucket cost
        (building int8 when none exists yet); an explicit dtype warms +
        parity-gates that variant via add_variant first. `threshold`
        overrides the calibration search — the same composed gate
        judges it (serve.py maps a refusal to 409). Returns the
        CascadeState now active; raises RuntimeError when the gate
        refuses (mv.cascade cleared, event logged) or the router cannot
        resolve pinned routes (fleet front — the CascadeFront then
        degrades every class to the plain live route)."""
        from distributedmnist_tpu.serve import cascade as cascade_mod

        with self._admin:
            if not getattr(self.router, "supports_alternates", False):
                raise RuntimeError(
                    "router does not support pinned-route alternates "
                    "(fleet front / engine double); a cascade needs "
                    "per-dtype dispatch on one routing table")
            if version is None:
                version = self.router.live_version()
                if version is None:
                    raise RuntimeError(
                        "no live version to enable a cascade on")
            with self._state:
                mv = self._get(version)
                if mv.state not in ("ready", "live"):
                    raise RuntimeError(
                        f"version {version!r} is {mv.state!r}; a cascade "
                        "hangs off a warmed version")
                ready = {dt: vi for dt, vi in mv.variants.items()
                         if vi.state == "ready" and vi.engines}
            if cheap_dtype == "auto":
                if ready:
                    def price(vi) -> float:
                        costs = vi.engine.bucket_costs()
                        return (sum(costs.values()) if costs
                                else float("inf"))
                    cheap_dtype = min(ready,
                                      key=lambda dt: price(ready[dt]))
                else:
                    cheap_dtype = "int8"
            if cheap_dtype in (None, "float32"):
                raise ValueError(
                    "the cascade's cheap stage must be a low-precision "
                    f"variant, not {cheap_dtype!r}")
            # validates cheap_dtype against PARITY_GATES, warms + gates
            # idempotently; a refused variant raises here
            vi = self.add_variant(version, cheap_dtype)
            rec = self._cascade_gate(mv, vi, threshold=threshold,
                                     max_escalation=max_escalation)
            if not rec["passed"]:
                with self._state:
                    mv.cascade = None
                    self._events.append({
                        "event": "cascade_refused", "version": version,
                        "cheap_dtype": cheap_dtype,
                        "reason": rec["why"],
                        # lint: allow[DML004] wall-clock event stamp for operators
                        "at": round(time.time(), 3)})
                raise RuntimeError(
                    f"cascade-accuracy gate REFUSED {cheap_dtype!r} "
                    f"cascade of {version!r}: {rec['why']}")
            state = cascade_mod.CascadeState(
                cheap_dtype=cheap_dtype, threshold=rec["threshold"],
                calibration=rec)
            with self._state:
                mv.cascade = state
                self._events.append({
                    "event": "cascade_enabled", "version": version,
                    "cheap_dtype": cheap_dtype,
                    "threshold": round(rec["threshold"], 6),
                    "escalation_fraction": rec["escalation_fraction"],
                    # lint: allow[DML004] wall-clock event stamp for operators
                    "at": round(time.time(), 3)})
            if self.router.live_version() == version:
                # composed cache entries and pinned routes must reflect
                # the NEW calibration the moment it exists
                self._refresh_live_routes(mv)
            log.info(
                "registry: cascade enabled on %s (%s, threshold %.4f, "
                "composed agreement %s, escalating %.1f%% of the "
                "calibration batch)", version, cheap_dtype,
                rec["threshold"], rec["composed_agreement"],
                100 * rec["escalation_fraction"])
            return state

    def set_cascade_threshold(self, version: str, threshold: float):
        """Re-gate `version`'s existing cascade at an operator-supplied
        threshold override (promote's `cascade_threshold` body field).
        The override is judged by the SAME composed-accuracy gate as a
        calibrated threshold — there is no bypass; a refusal raises
        RuntimeError (→ 409) and leaves the previous state intact."""
        from distributedmnist_tpu.serve import cascade as cascade_mod

        with self._admin:
            with self._state:
                mv = self._get(version)
                state = mv.cascade
            if state is None:
                raise RuntimeError(
                    f"version {version!r} has no cascade to "
                    "re-threshold; enable one first")
            vi = mv.variants.get(state.cheap_dtype)
            if vi is None or vi.state != "ready" or not vi.engines:
                raise RuntimeError(
                    f"cascade variant {state.cheap_dtype!r} of "
                    f"{version!r} is no longer ready; re-enable the "
                    "cascade")
            rec = self._cascade_gate(
                mv, vi, threshold=threshold,
                max_escalation=state.calibration.get("max_escalation",
                                                     0.5))
            if not rec["passed"]:
                raise RuntimeError(
                    f"cascade threshold override {threshold!r} REFUSED "
                    f"for {version!r}: {rec['why']}")
            new = cascade_mod.CascadeState(
                cheap_dtype=state.cheap_dtype,
                threshold=rec["threshold"], calibration=rec)
            with self._state:
                mv.cascade = new
                self._events.append({
                    "event": "cascade_threshold_set", "version": version,
                    "threshold": round(rec["threshold"], 6),
                    # lint: allow[DML004] wall-clock event stamp for operators
                    "at": round(time.time(), 3)})
            if self.router.live_version() == version:
                self._refresh_live_routes(mv)
            return new

    def cascade_plan(self) -> Optional[tuple]:
        """(live version, CascadeState) when the live version has a
        calibrated cascade — the CascadeFront's per-submit read. None
        otherwise (warming, uncascaded version): every accuracy class
        then degrades to the plain live route, counted in metrics."""
        live = self.router.live_version()
        if live is None:
            return None
        with self._state:
            mv = self._versions.get(live)
            if mv is None or mv.cascade is None:
                return None
            return (live, mv.cascade)

    # -- routing -----------------------------------------------------------

    def set_cache(self, cache) -> None:
        """Install the prediction cache this registry invalidates on
        every live-route change (ISSUE 10). Any object with an
        `invalidate(reason=)` method works — serve/cache.py's
        PredictionCache in production."""
        self._cache = cache

    def _route_set(self, kind: str, mv: ModelVersion,
                   fraction: Optional[float] = None,
                   engines: Optional[list] = None) -> None:
        """One routing mutation, fanned out fleet-wide: a ReplicaSet
        takes the whole per-replica engine list under its pick lock (no
        batch dispatches mid-roll); a plain Router takes the single
        engine — same call sites, no drift between the two shapes.
        `engines` overrides the version's base engine list (a dtype
        variant routing under the same version label).

        A live-target change also invalidates the prediction cache
        ATOMICALLY with the swap (promote/rollback hold _state across
        both, so no lookup can land between the new route and the
        flush): cached bytes are keyed by the live route, so entries
        written under the old route are unreachable the instant
        set_live returns — the invalidation reclaims their memory and
        bumps the cache epoch so in-flight single-flight inserts that
        raced the swap are dropped, never served (ISSUE 10)."""
        engines = mv.engines if engines is None else engines
        target = (list(engines) if self.n_replicas > 1 else engines[0])
        if kind == "live":
            if (self.n_replicas == 1
                    and getattr(self.router, "supports_alternates",
                                False)):
                # Pinned-route table (ISSUE 17): the f32 base plus every
                # parity-passing ready variant of THIS version, swapped
                # atomically with the live target so a cascade stage
                # dispatch can never straddle a promote boundary.
                alternates = {"float32": mv.engines[0]}
                for dt, vi in mv.variants.items():
                    if vi.state == "ready" and vi.engines:
                        alternates[dt] = vi.engines[0]
                self.router.set_live(target, mv.version,
                                     alternates=alternates)
            else:
                self.router.set_live(target, mv.version)
            if self._cache is not None:
                self._cache.invalidate(reason=f"live -> {mv.version}")
        elif kind == "shadow":
            self.router.set_shadow(target, mv.version, fraction)
        else:
            self.router.set_canary(target, mv.version, fraction)

    def promote(self, version: str,
                infer_dtype: Optional[str] = None,
                cascade_threshold: Optional[float] = None
                ) -> ModelVersion:
        """Atomic hot-swap: `version` (which must be warmed: 'ready' or
        already 'live') becomes the live target. The demoted version
        stays resident in state 'ready' — rollback is promote(old).
        `infer_dtype` routes one of the version's gated low-precision
        variants instead of the f32 base ('float32'/None = base); a
        variant that is not parity-passing ready is refused here too —
        the gate has no promote-time bypass. `cascade_threshold`
        re-gates the version's cascade at that override BEFORE the swap
        (a refused override aborts the promote — the old live keeps
        serving)."""
        with self._admin:
            if cascade_threshold is not None:
                # validates via the composed-accuracy gate; RuntimeError
                # (no cascade / gate refusal) propagates before any
                # routing change. _admin is re-entrant; _state is not
                # held here.
                self.set_cascade_threshold(version, cascade_threshold)
            return self._promote_locked(version, infer_dtype)

    def _promote_locked(self, version: str,
                        infer_dtype: Optional[str]) -> ModelVersion:
        with self._state:
            mv = self._get(version)
            if mv.state not in ("ready", "live"):
                raise RuntimeError(
                    f"version {version!r} is {mv.state!r}; only a warmed "
                    "('ready') version may take live traffic")
            engines = None
            if infer_dtype not in (None, "float32"):
                vi = mv.variants.get(infer_dtype)
                if vi is None or vi.state != "ready" or not vi.engines:
                    why = (vi.last_error if vi is not None
                           else "never warmed")
                    raise RuntimeError(
                        f"variant {infer_dtype!r} of {version!r} is not "
                        f"promotable ({why}); only a parity-passing "
                        "ready variant may take traffic")
                engines = vi.engines
            prev = self.router.live_version()
            self._route_set("live", mv, engines=engines)
            mv.state = "live"
            if prev is not None and prev != version:
                old = self._versions.get(prev)
                if old is not None:
                    old.state = "ready"
            self._evict_locked(protect={version})
            return mv

    def rollback(self, from_version: str, reason: str
                 ) -> Optional[ModelVersion]:
        """Demote `from_version` (if still live) and promote the newest
        HEALTHY resident — warmed ('ready'), engine resident, no
        recorded error — emitting a rollback event. The circuit
        breaker's trip path (serve/resilience.py), callable by an
        operator too. The demoted version stays resident but gets
        `reason` as its last_error, which excludes it from being
        auto-promoted right back (a flapping rollback would be worse
        than none). Returns the newly live ModelVersion; None when
        `from_version` is no longer live (someone already rolled) or no
        healthy fallback exists (the event records that too — serving
        then keeps limping on the tripped version, which still beats an
        empty routing table's hard 503)."""
        with self._admin, self._state:
            live = self.router.live_version()
            if live != from_version:
                log.info("rollback from %s skipped: live is already %s",
                         from_version, live)
                return None
            candidates = [
                mv for name, mv in self._versions.items()
                if name != from_version and mv.state == "ready"
                and mv.engines and mv.last_error is None]
            # lint: allow[DML004] wall-clock event stamps; the fallback pick below orders by loaded_mono
            now = time.time()
            old = self._versions.get(from_version)
            if not candidates:
                self._events.append({
                    "event": "rollback_failed", "from": from_version,
                    "to": None, "reason": reason, "at": round(now, 3)})
                log.error(
                    "rollback from %s FAILED: no healthy resident "
                    "fallback (%s); keeping the tripped version live",
                    from_version, reason)
                return None
            # Monotonic ordering: a wall-clock step between two loads
            # must not make an older version read as "newest healthy".
            target = max(candidates, key=lambda mv: mv.loaded_mono)
            # promote()'s core, inlined: _state is a plain Lock (not
            # re-entrant) and the demotion must also stamp last_error
            # atomically with the swap.
            self._route_set("live", target)
            target.state = "live"
            if old is not None:
                old.state = "ready"
                old.record_error(reason)
            self._events.append({
                "event": "rollback", "from": from_version,
                "to": target.version, "reason": reason,
                "at": round(now, 3)})
            log.warning("rollback: %s -> %s (%s)", from_version,
                        target.version, reason)
            return target

    def events(self) -> list:
        """Lifecycle events, oldest first (bounded window): rollbacks
        and rollback failures — what /healthz and GET /models surface."""
        with self._state:
            return list(self._events)

    def set_shadow(self, version: str, fraction: float = 0.1
                   ) -> ModelVersion:
        """Duplicate `fraction` of live traffic to `version`; its results
        are compared + discarded, never returned to clients."""
        with self._admin, self._state:
            mv = self._get(version)
            if mv.state != "ready":
                raise RuntimeError(
                    f"version {version!r} is {mv.state!r}; only a warmed "
                    "non-live version can shadow")
            self._route_set("shadow", mv, fraction)
            return mv

    def set_canary(self, version: str, fraction: float = 0.1
                   ) -> ModelVersion:
        """Route `fraction` of traffic to `version` for real, with
        version-tagged metrics separating the two populations."""
        with self._admin, self._state:
            mv = self._get(version)
            if mv.state != "ready":
                raise RuntimeError(
                    f"version {version!r} is {mv.state!r}; only a warmed "
                    "non-live version can take canary traffic")
            self._route_set("canary", mv, fraction)
            return mv

    def clear_candidates(self) -> None:
        self.router.clear_candidates()

    # -- introspection -----------------------------------------------------

    def _get(self, version: str) -> ModelVersion:
        mv = self._versions.get(version)
        if mv is None:
            raise KeyError(f"unknown version {version!r}; loaded: "
                           f"{sorted(self._versions)}")
        return mv

    def get(self, version: str) -> ModelVersion:
        with self._state:
            return self._get(version)

    def live_version(self) -> Optional[str]:
        return self.router.live_version()

    def describe(self) -> dict:
        """GET /models payload: every resident version plus the routing
        table."""
        # _state only — never blocked by an in-flight warmup, so
        # /healthz and GET /models answer during a multi-second load
        with self._state:
            live_dtype = getattr(self.router, "live_infer_dtype",
                                 lambda: None)()
            return {
                "versions": [mv.describe()
                             for mv in self._versions.values()],
                "routes": self.router.routes(),
                # which precision the LIVE engines actually serve
                # (ISSUE 7 satellite: an operator must be able to tell)
                "live_infer_dtype": live_dtype,
                "events": list(self._events),
                "max_versions": self.max_versions,
                "checkpoint_dir": self.checkpoint_dir,
                "buckets": list(self.factory.buckets),
                "max_batch": self.factory.max_batch,
                "replicas": self.n_replicas,
            }

    # -- eviction ----------------------------------------------------------

    def _evict_locked(self, protect: set = frozenset()) -> None:
        """Drop oldest routeless versions past max_versions (caller
        holds _state). 'failed' entries are dropped first (they hold no
        engine); versions in `protect` (the one just added/promoted) are
        never candidates — eviction must not swallow the entry whose
        operation triggered it. An engine still referenced by in-flight
        handles is freed only after its last fetch — handles pin their
        engine, so eviction can never yank a batch's program out from
        under it."""
        in_route = self.router.versions_in_route()
        while len(self._versions) > self.max_versions:
            for name, mv in list(self._versions.items()):
                if name in protect:
                    continue
                if mv.state == "failed" or (
                        mv.state == "ready" and name not in in_route):
                    del self._versions[name]
                    log.info("registry: evicted %s (%s)", name, mv.state)
                    break
            else:
                return            # everything left is live or in-route


def build_serving(cfg, metrics=None):
    """(registry, router, factory) from a Config — the multi-version
    sibling of engine.build_engine. No version is loaded yet: callers
    decide boot order (serve.py bootstraps in a warm thread so /healthz
    can report 'warming' while the HTTP server is already up).

    cfg.serve_replicas > 1 (ISSUE 6) returns a ReplicaSet in the router
    slot — engine-shaped, so every downstream consumer (batcher,
    serve.py, bench) is fleet-or-single agnostic; serve_replicas == 1
    keeps the bare Router (a one-member fleet is pure overhead)."""
    from distributedmnist_tpu.serve.engine import build_model_and_mesh

    model, mesh, dtype = build_model_and_mesh(cfg)
    factory = EngineFactory(model, mesh, dtype=dtype,
                            max_batch=cfg.serve_max_batch,
                            replicas=cfg.serve_replicas,
                            fused=cfg.fused_kernels)
    if cfg.serve_replicas > 1:
        router = factory.make_fleet(
            metrics=metrics, seed=cfg.seed,
            per_replica_inflight=cfg.serve_replica_inflight,
            hedge=cfg.serve_hedge)
    else:
        router = factory.make_router(metrics=metrics, seed=cfg.seed)
    registry = ModelRegistry(factory, router,
                             checkpoint_dir=cfg.checkpoint_dir,
                             max_versions=cfg.serve_max_versions)
    return registry, router, factory
