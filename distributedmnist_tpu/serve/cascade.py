"""Confidence-gated model cascade (ISSUE 17): int8 goodput at f32
accuracy.

PAPERS.md cites Clipper for batching/admission; this module is its
model-SELECTION layer. The cheap variant (int8, or the megakernel where
gated) answers every request first; rows whose softmax margin (top-1
minus top-2 probability) clears a CALIBRATED confidence threshold are
served as-is, and the uncertain remainder is re-submitted to the f32
reference THROUGH THE NORMAL COALESCING PATH — an escalation is just a
request, so the DP batch former, the bounded in-flight window, cache
keying and bisection semantics all hold unchanged. Clockwork's
predictability argument prices the decision: both stages run
pre-compiled, shape-stable programs whose costs are already in the
bucket cost table, so a cascade never compiles anything.

The threshold is not a config knob: it is CALIBRATED per version on the
registry's held-out parity batch (calibrate below) — the smallest
escalation set whose COMPOSED accuracy (escalated rows answered by f32,
the rest by the cheap variant) matches f32 within the PARITY.md
agreement bar, with every known-disagreeing row escalated. That is the
END-TO-END cascade-accuracy gate: a cascade is only promotable when the
composition passes, exactly like a single variant must pass its parity
gate. The one calibrated threshold accessor is `threshold_of` — lint
DML016 refuses any other serve-side code path that reads per-row
margins or hardcodes a confidence constant.

Request surface (serve.py `X-Accuracy-Class`):

    fast      cheap-variant only — int8 latency, int8 accuracy
    balanced  the cascade — cheap answers confident rows, f32 the rest
    exact     f32 only — bypasses the cheap stage entirely

CascadeFront sits in front of the CacheFront (or bare batcher),
submit-shaped. Composed (balanced) results insert into the prediction
cache under the dedicated `cascade:<dtype>` route label, and the two
stages ride the normal per-dtype cache labels — a cheap-only answer can
therefore never be served to an `exact`-class request (ISSUE 17
satellite; the class-confusion test pins it).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from distributedmnist_tpu.serve import trace

# The per-request accuracy classes serve.py's X-Accuracy-Class header
# selects (400 on anything else).
ACCURACY_CLASSES = ("fast", "balanced", "exact")

# Composed results are cached under this route-label prefix: distinct
# from every single-dtype label, so a cascade answer can never alias a
# cheap-only or f32-only entry.
CASCADE_LABEL_PREFIX = "cascade:"


def cascade_label(cheap_dtype: str) -> str:
    """The prediction-cache route label composed results live under."""
    return CASCADE_LABEL_PREFIX + cheap_dtype


def softmax_margin(logits) -> np.ndarray:
    """Per-row confidence margin: softmax(top-1) - softmax(top-2),
    float64 in [0, 1]. Pure host numpy — the margin read happens on
    result bytes already fetched, so the cascade adds no traced jit
    keys (the compile-surface auditor's universe stays closed)."""
    z = np.asarray(logits, dtype=np.float64)
    z = z - z.max(axis=-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=-1, keepdims=True)
    top2 = np.partition(p, -2, axis=-1)[..., -2:]
    return top2[..., 1] - top2[..., 0]


def _composed(margins, agree, threshold: float) -> tuple:
    """(composed agreement, escalation fraction) at a given threshold:
    rows with margin < threshold are answered by the reference (always
    agree with it); the rest keep the cheap answer."""
    esc = margins < threshold
    composed = float(np.mean(np.where(esc, True, agree)))
    return composed, float(np.mean(esc))


def calibrate(ref_logits, cheap_logits, min_agreement: float,
              threshold: Optional[float] = None,
              max_escalation: float = 0.5) -> dict:
    """Calibrate (or, with `threshold` given, validate) the cascade's
    confidence threshold on the held-out parity batch — the END-TO-END
    cascade-accuracy gate.

    Search rule: sort rows by cheap-stage margin ascending; escalating
    the k lowest-margin rows yields a composed agreement of
    (k + agreements among the rest) / n. The calibrated k is the
    smallest that (a) clears `min_agreement` AND (b) escalates every
    row the cheap stage got WRONG on this batch (low margin correlates
    with, but does not equal, disagreement — the gate must not leave a
    known disagreement un-escalated), capped at `max_escalation`·n
    (past half the batch the cascade is slower than f32 and the gate
    should refuse rather than quietly serve a worse-than-baseline
    route). The threshold is the midpoint between the k-th and
    (k+1)-th sorted margins, then the record's numbers are re-measured
    at that REALIZED threshold (margin ties can shrink the escalated
    set). passed=False (with why) when no threshold under the cap
    reaches the bar.

    With `threshold` given (a promote-time operator override) the
    search is skipped and the same composed gate judges that value —
    serve.py maps a refusal to 409."""
    ref = np.asarray(ref_logits)
    cheap = np.asarray(cheap_logits)
    if ref.shape != cheap.shape:
        raise ValueError(
            f"logit shapes differ: reference {ref.shape} vs cheap "
            f"{cheap.shape}")
    n = ref.shape[0]
    margins = softmax_margin(cheap)
    agree = ref.argmax(-1) == cheap.argmax(-1)
    base = float(np.mean(agree))
    source = "calibrated"
    if threshold is None:
        order = np.argsort(margins, kind="stable")
        ms = margins[order]
        ag = agree[order]
        # composed_k[k]: agreement when exactly the k lowest-margin
        # rows escalate (they all agree by construction)
        suffix = np.concatenate([np.cumsum(ag[::-1])[::-1], [0.0]])
        composed_k = (np.arange(n + 1) + suffix) / n
        meets = np.nonzero(composed_k >= min_agreement)[0]
        k_bar = int(meets[0]) if meets.size else n
        wrong = np.nonzero(~ag)[0]
        k_full = int(wrong[-1]) + 1 if wrong.size else 0
        k_cap = int(np.floor(max_escalation * n))
        k = min(max(k_bar, k_full), k_cap)
        if k <= 0:
            threshold = 0.0
        elif k >= n:
            threshold = float(np.nextafter(ms[-1], np.inf))
        elif ms[k - 1] == ms[k]:
            # tie across the cut: a strict `< threshold` rule cannot
            # split it, so the realized escalation set is smaller
            threshold = float(ms[k])
        else:
            threshold = float((ms[k - 1] + ms[k]) / 2.0)
    else:
        source = "override"
        threshold = float(threshold)
    composed, esc_frac = _composed(margins, agree, threshold)
    why = None
    if composed < min_agreement:
        why = (f"composed argmax agreement {composed:.4f} < "
               f"{min_agreement} at threshold {threshold:.4f} "
               f"(escalating {esc_frac:.1%} of {n} rows, cap "
               f"{max_escalation:.0%}; cheap-only agreement {base:.4f})")
    return {
        "passed": why is None,
        "why": why,
        "threshold": threshold,
        "rows": int(n),
        "base_agreement": round(base, 6),
        "composed_agreement": round(composed, 6),
        "escalation_fraction": round(esc_frac, 6),
        "min_agreement": min_agreement,
        "max_escalation": max_escalation,
        "source": source,
    }


@dataclasses.dataclass
class CascadeState:
    """A version's calibrated cascade: which cheap variant answers
    first, the one threshold every margin read routes through
    (threshold_of — lint DML016), and the calibration record the
    cascade-accuracy gate produced."""

    cheap_dtype: str
    threshold: float
    calibration: dict

    def describe(self) -> dict:
        return {
            "cheap_dtype": self.cheap_dtype,
            "threshold": round(self.threshold, 6),
            "calibration": self.calibration,
        }


def threshold_of(state: CascadeState) -> float:
    """THE calibrated confidence threshold accessor. Every serve-side
    margin comparison must route through this one value (lint DML016):
    a hardcoded confidence constant would silently desynchronize the
    escalation rule from the gate that proved the composition
    accurate."""
    return float(state.threshold)


class CascadeFront:
    """Submit-shaped cascade layer over the CacheFront (or the bare
    batcher): partitions cheap-stage results by calibrated margin and
    re-submits the uncertain slice to f32 through the normal coalescing
    path. Stage-2 submission happens inside stage 1's done-callback,
    which the batcher runs BEFORE the stage-1 segment leaves its
    in-flight count — so "pending==0 and inflight==0" still proves a
    drained pipeline with the cascade in front.

    With no calibrated cascade on the live version (warming, or a
    promote to an uncascaded version) every class degrades to the plain
    live route — counted in metrics, never an error: the transient
    window between promote and re-calibration must shed accuracy
    guarantees loudly, not availability."""

    # serve.py's handler keys off this marker (engine doubles and the
    # cache front don't have it) to accept X-Accuracy-Class.
    is_cascade_front = True

    def __init__(self, inner, batcher, router, registry, metrics=None,
                 cache=None, default_class: str = "balanced"):
        from distributedmnist_tpu.serve.cache import CacheFront

        if default_class not in ACCURACY_CLASSES:
            raise ValueError(
                f"unknown default accuracy class {default_class!r} "
                f"(expected one of {ACCURACY_CLASSES})")
        self.inner = inner
        self.batcher = batcher
        self.router = router
        self.registry = registry
        self.metrics = metrics
        self.cache = cache
        self.default_class = default_class
        self._inner_labeled = isinstance(inner, CacheFront)

    # -- engine-shaped proxies (bench drain predicate, serve.py) ----------

    def pending_rows(self) -> int:
        return self.inner.pending_rows()

    def inflight_batches(self) -> int:
        return self.inner.inflight_batches()

    def stop(self, drain: bool = True) -> None:
        self.inner.stop(drain=drain)

    # -- submission --------------------------------------------------------

    def _plan(self):
        """(live version, CascadeState) when the live version has a
        calibrated cascade, else None."""
        plan = getattr(self.registry, "cascade_plan", None)
        return plan() if callable(plan) else None

    def _inner_submit(self, x, deadline_s, route, label) -> Future:
        """Route a stage through the inner layer: the CacheFront keys
        the entry under `label` (so per-class populations never alias);
        a bare batcher just pins the dispatch route."""
        if self._inner_labeled:
            return self.inner.submit(x, deadline_s=deadline_s,
                                     route=route, route_label=label)
        return self.inner.submit(x, deadline_s=deadline_s, route=route)

    def submit(self, x, deadline_s: Optional[float] = None,
               accuracy_class: Optional[str] = None) -> Future:
        cls = accuracy_class or self.default_class
        if cls not in ACCURACY_CLASSES:
            raise ValueError(
                f"unknown accuracy class {cls!r} (expected one of "
                f"{ACCURACY_CLASSES})")
        if self.metrics is not None:
            self.metrics.record_cascade_class(cls)
        plan = self._plan()
        if plan is None:
            # no calibrated cascade on the live version: the plain live
            # route serves (degradation is counted, never silent)
            if self.metrics is not None:
                self.metrics.record_cascade_degraded()
            return self.inner.submit(x, deadline_s=deadline_s)
        version, state = plan
        if cls == "exact":
            return self._inner_submit(x, deadline_s, "float32", "float32")
        if cls == "fast":
            return self._inner_submit(x, deadline_s, state.cheap_dtype,
                                      state.cheap_dtype)
        return self._balanced(x, deadline_s, version, state)

    def _balanced(self, x, deadline_s, version: str,
                  state: CascadeState) -> Future:
        x = self.router._as_images(x)
        n = x.shape[0]
        t0 = time.monotonic()
        label = cascade_label(state.cheap_dtype)
        rid = self.batcher.next_rid()
        out: Future = Future()
        tr = trace.active()
        tid = None
        if tr is not None:
            tid = tr.start_request(rid, rows=n, deadline_s=deadline_s,
                                   t0=t0)
            out.trace_id = tid
        key = epoch = None
        if self.cache is not None:
            from distributedmnist_tpu.serve.cache import content_key

            key = content_key(version, label, x)
            t_lk = time.monotonic()
            cached = self.cache.lookup(key)
            trace.add_span("cache.lookup", t_lk, time.monotonic(),
                           rids=(rid,), hit=cached is not None)
            if cached is not None:
                t_hit = time.monotonic()
                trace.add_span("cache.hit", t0, t_hit, rids=(rid,))
                if tr is not None:
                    tr.finish_request(rid, t_end=t_hit)
                if self.metrics is not None:
                    self.metrics.record_cache_hit(
                        t_hit - t0, rows=n, version=version,
                        infer_dtype=label)
                out.version = version
                out.set_result(cached)
                return out
            epoch = self.cache.epoch()
        ctx = {"x": x, "n": n, "t0": t0, "rid": rid, "tid": tid,
               "version": version, "state": state, "key": key,
               "epoch": epoch, "deadline_s": deadline_s, "out": out,
               "label": label}
        try:
            f1 = self._inner_submit(x, deadline_s, state.cheap_dtype,
                                    state.cheap_dtype)
        except BaseException:
            # never admitted: nothing will ever finish this trace
            if tr is not None:
                tr.abort_request(rid)
            raise
        f1.add_done_callback(lambda f: self._stage1_done(ctx, f))
        return out

    def _stage1_done(self, ctx: dict, f1: Future) -> None:
        try:
            logits1 = f1.result()
        except BaseException as e:
            self._finish(ctx, error=e)
            return
        t1 = time.monotonic()
        state = ctx["state"]
        rid = ctx["rid"]
        margins = softmax_margin(logits1)
        esc = margins < threshold_of(state)
        n_esc = int(esc.sum())
        trace.add_span("cascade.stage", ctx["t0"], t1, rids=(rid,),
                       stage=state.cheap_dtype, rows=ctx["n"],
                       escalated=n_esc)
        if self.metrics is not None:
            self.metrics.record_cascade_stage(state.cheap_dtype,
                                              ctx["n"])
        v1 = getattr(f1, "version", None)
        if n_esc == 0:
            self._finish(ctx, logits=np.asarray(logits1), v1=v1, v2=v1)
            return
        trace.add_span("cascade.escalate", t1, t1, rids=(rid,),
                       rows=n_esc, threshold=round(threshold_of(state),
                                                   6))
        if self.metrics is not None:
            self.metrics.record_cascade_escalation(n_esc)
        idx = np.nonzero(esc)[0]
        try:
            # the escalation inherits the request's deadline: under
            # deadline pressure it is shed exactly like any request
            f2 = self._inner_submit(ctx["x"][idx], ctx["deadline_s"],
                                    "float32", "float32")
        except BaseException as e:
            self._finish(ctx, error=e)
            return
        f2.add_done_callback(
            lambda f: self._stage2_done(ctx, np.asarray(logits1), idx,
                                        v1, t1, f))

    def _stage2_done(self, ctx: dict, logits1, idx, v1, t1,
                     f2: Future) -> None:
        try:
            logits2 = f2.result()
        except BaseException as e:
            self._finish(ctx, error=e)
            return
        t2 = time.monotonic()
        # reassembly is byte-stable: rows are independent through every
        # engine forward (padding is zero rows the slice drops), so an
        # escalated row's bytes are exactly the f32 single-dtype bytes
        composed = np.array(logits1)
        composed[idx] = logits2
        trace.add_span("cascade.stage", t1, t2, rids=(ctx["rid"],),
                       stage="float32", rows=int(len(idx)))
        if self.metrics is not None:
            self.metrics.record_cascade_stage("float32", int(len(idx)))
        self._finish(ctx, logits=composed, v1=v1,
                     v2=getattr(f2, "version", None))

    def _finish(self, ctx: dict, logits=None, v1=None, v2=None,
                error=None) -> None:
        """Resolve the composed request: trace finishes BEFORE the
        future resolves (the Server-Timing contract), and the composed
        bytes insert into the cache only when both stages ran the same
        version this request was keyed under (and the epoch still
        matches — the cache itself re-checks under its lock)."""
        out = ctx["out"]
        version = v1 if v1 == v2 else None
        if (error is None and ctx["key"] is not None
                and version is not None
                and version == ctx["version"]):
            self.cache.insert(ctx["key"], logits, version,
                              ctx["label"], epoch=ctx["epoch"])
        tr = trace.active()
        if tr is not None and ctx["tid"] is not None:
            tr.finish_request(ctx["rid"], error=error)
        if error is not None:
            out.set_exception(error)
            return
        out.version = version
        out.set_result(logits)
