"""Fault-tolerant replica fleet (ISSUE 6): health-tracked dispatch,
failover redispatch, and hedged tails over N engine replicas.

Everything serve-side before this PR ran ONE engine on one mesh: a
wedged or faulted engine was a full outage, and PR 5's circuit breaker
could only roll the *version*, not route around a sick *replica*. This
module makes redundancy — not just retry — the failure-handling
primitive, the way Clockwork isolates workers behind a controller that
stops sending to lagging ones and Clipper sheds at the front door
instead of absorbing a sick backend's queueing delay (PAPERS.md).

A **ReplicaSet** is engine-shaped (dispatch()/fetch(), max_batch /
buckets / platform, _as_images, bucket_costs) so it sits exactly where
the single Router sits today — the batcher cannot tell the difference.
Inside, N replicas each own a full per-replica Router over their own
InferenceEngines (mesh-slice devices when the host has enough chips,
N logical replicas sharing the mesh otherwise; serve/registry.py fans
every version's warm + promote out to all of them, so a roll never
leaves the fleet serving mixed versions). Per dispatch:

- **cost-aware least-loaded pick**: each replica holds a bounded
  in-flight window (`per_replica_inflight` batches) and an outstanding
  cost gauge priced by the PR 4 warmup-measured bucket cost tables;
  the pick takes the cheapest-backlog healthy replica, with total
  dispatches as the tiebreak (degrades to round-robin when no cost
  table exists yet).
- **health-tracked exclusion**: every batch outcome feeds a
  per-replica sliding-window HealthTracker AND a per-replica
  CircuitBreaker (serve/resilience.py). A tripped replica is excluded
  from picks until its cooldown lapses — automatic drain on sickness,
  automatic rejoin on recovery. If EVERY replica is tripped the pick
  degrades to least-loaded anyway (limp mode): a grim health window
  must never turn into a self-inflicted total outage.
- **failover redispatch**: a batch whose replica dies at dispatch or
  fetch is retried ONCE on a healthy sibling before the failure ever
  reaches the batcher (where PR 5 bisection would run) — a replica
  fault costs latency, not errors. The handle keeps the host-side
  payload until fan-out precisely so a fetch-side death can be
  re-dispatched; failovers re-tag the handle's (version, replica) so
  attribution follows the replica that actually computed the result.
  503-shaped errors (NoLiveModel while warming) are systemic, not
  replica faults: every sibling would refuse identically, so they
  propagate without failover or health blame.
- **hedged dispatch** (optional, `serve_hedge`): a batch that reaches
  its fetch already slower than `hedge_factor x` the live p95 cost
  estimate for its bucket (the engine's warmup-measured tail table) is
  raced against a duplicate on a free healthy sibling; first result
  wins, the loser drains in the background. Tail latency from a slow-
  but-alive replica is bounded by a fresh dispatch elsewhere — the
  classic tail-at-scale hedge, gated so it only spends duplicate work
  when the tail is already blown and a sibling has spare capacity.
- **drain / rejoin**: `drain(rid)` removes a replica from the pick set
  while its in-flight batches finish (admin POST /replicas/{id}/drain);
  `rejoin(rid)` restores it with a fresh health slate. Draining the
  last active replica is refused — an operator emptying the fleet by
  accident should get a 409, not an outage.

Failpoints `replica.dispatch` / `replica.fetch` (serve/faults.py) wrap
the per-replica hops with ctx={replica, ...}, so a chaos schedule can
kill exactly one replica (`replica.fetch:p=1,replica=r1`) and the bench
can prove the storm is absorbed by failover: availability 1.0, zero
recompiles (rescue and hedge dispatches reuse compiled bucket programs
on the sibling — never a new shape).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional, Sequence

import numpy as np

from distributedmnist_tpu.analysis.locks import make_condition, make_thread
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.batcher import resolve_max_inflight
from distributedmnist_tpu.serve.engine import InferenceEngine
from distributedmnist_tpu.serve.faults import failpoint
from distributedmnist_tpu.serve.resilience import (CircuitBreaker,
                                                   HealthTracker)

log = logging.getLogger("distributedmnist_tpu")


class NoReplicaAvailable(RuntimeError):
    """Every replica is draining (or the fleet is empty): new work is
    shed with 503 semantics — systemic like NoLiveModel, so the batcher
    neither bisects it nor blames a version or replica for it."""

    status = 503


@dataclasses.dataclass
class _Replica:
    """One member of the fleet: its Router plus the live accounting the
    pick runs on. All mutable fields are guarded by the ReplicaSet's
    condition lock."""

    rid: str
    router: Any
    state: str = "active"            # "active" | "draining"
    inflight: int = 0                # reserved dispatch slots
    outstanding_s: float = 0.0       # est. cost of reserved work
    last_pick: int = 0               # fleet pick sequence, LRU tiebreak
    dispatched_batches: int = 0
    dispatched_rows: int = 0
    failures: int = 0


def select_member(active: Sequence[Any], cooled, window: int,
                  overflow: bool = False,
                  healthy_only: bool = False) -> Optional[Any]:
    """The fleet's cost-aware least-loaded pick as a pure function —
    one policy, two callers: ReplicaSet._pick (in-process replicas)
    and the gateway's balanced dispatch across worker processes
    (serve/gateway.py, ISSUE 19). Members need the accounting triple
    (`rid`, `inflight`, `outstanding_s`, `last_pick`); `cooled` is the
    breaker predicate (rid -> in cooldown?).

    Healthy members with free window credit win by least outstanding
    work; every member cooled degrades to least-loaded among active
    (limp mode — a grim health window is never a self-inflicted
    outage) unless `healthy_only` (hedge semantics: a duplicate on a
    sick member is guaranteed wasted work). `overflow` lets the pick
    exceed the window (rescue semantics). Returns None when no
    candidate qualifies — selection only: the CALLER reserves the
    slot under its own lock."""
    if not active:
        return None
    healthy = [m for m in active if not cooled(m.rid)]
    if healthy_only and not healthy:
        return None
    pool = healthy or active        # limp mode
    free = [m for m in pool if m.inflight < window]
    cands = free or (pool if overflow else [])
    if not cands:
        return None
    # Ties (idle symmetric members) break by LEAST RECENTLY PICKED —
    # stateless round-robin. A cumulative-count tiebreak would flood a
    # freshly rejoined member until its lifetime total caught up with
    # siblings that served through its absence.
    return min(cands, key=lambda m: (m.outstanding_s, m.inflight,
                                     m.last_pick))


@dataclasses.dataclass
class FleetHandle:
    """A dispatched batch plus everything failover needs: the replica
    that holds it, the reserve cost to release at completion, and the
    ORIGINAL host payload — a fetch-side replica death can only be
    redispatched because the input outlives the staging buffer. The
    (version, replica) tags are re-stamped when failover or a winning
    hedge moves the computation, so the batcher's metrics attribution
    always names the replica/version that produced the result."""

    inner: Any                      # the replica Router's RoutedHandle
    replica: str
    version: Optional[str]
    n: int
    bucket: int
    x: Any                          # host payload, for redispatch
    cost_s: float                   # reserved estimate, released as-is
    t_dispatch: float
    # serving precision of the computing engine (ISSUE 7); re-stamped
    # alongside (version, replica) when failover or a winning hedge
    # moves the computation
    infer_dtype: Optional[str] = None


class ReplicaSet:
    """Engine-shaped load-balancing dispatcher over N replica Routers.

    The registry drives the version surface (set_live/set_shadow/
    set_canary fan out to every replica under the fleet lock, so no
    batch can be picked mid-roll); the batcher drives dispatch()/
    fetch() exactly as it drives a single Router. n_replicas >= 2:
    a one-replica fleet is just a Router with overhead — build_serving
    keeps the single-router path for that."""

    HEDGE_FACTOR = 3.0

    def __init__(self, routers: Sequence, metrics=None,
                 per_replica_inflight: Optional[int] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 health: Optional[HealthTracker] = None,
                 hedge: bool = False,
                 hedge_factor: Optional[float] = None):
        if len(routers) < 2:
            raise ValueError(
                f"a fleet needs >= 2 replicas, got {len(routers)} "
                "(single-replica serving uses a bare Router)")
        first = routers[0]
        for r in routers[1:]:
            if (tuple(r.buckets) != tuple(first.buckets)
                    or r.max_batch != first.max_batch):
                raise ValueError(
                    "replica geometry mismatch: all replicas must share "
                    "one bucket ladder / max_batch")
        self.replicas = [_Replica(rid=r.replica or f"r{i}", router=r)
                         for i, r in enumerate(routers)]
        self._by_id = {r.rid: r for r in self.replicas}
        if len(self._by_id) != len(self.replicas):
            raise ValueError("duplicate replica ids")
        self.max_batch = first.max_batch
        self.buckets = tuple(first.buckets)
        self.platform = first.platform
        self.n_chips = first.n_chips           # PER-REPLICA chip count
        self.metrics = metrics
        self.per_replica_inflight = resolve_max_inflight(
            per_replica_inflight, self.platform)
        # A tighter default window than the version breaker: a replica
        # is cheap to exclude (siblings absorb its share) and cheap to
        # re-admit (cooldown lapse), so trip fast, recover fast.
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            window_s=5.0, min_requests=8, failure_ratio=0.5,
            cooldown_s=10.0)
        self.health = health if health is not None else HealthTracker()
        self.hedge = hedge
        self.hedge_factor = (self.HEDGE_FACTOR if hedge_factor is None
                             else hedge_factor)
        self._cond = make_condition("fleet.pick")
        self._pick_seq = 0
        self._failovers_dispatch = 0
        self._failovers_fetch = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._replica_trips = 0

    # Engine-shape parity (same borrow the Router makes).
    _as_images = staticmethod(InferenceEngine._as_images)
    bucket_for = InferenceEngine.bucket_for

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    @property
    def max_inflight_total(self) -> int:
        """The fleet's aggregate dispatch window: the batcher sizes its
        own in-flight semaphore to this when serve_max_inflight is
        left on auto, so the queue keeps every replica's window fed."""
        return self.per_replica_inflight * len(self.replicas)

    def replica_ids(self) -> list[str]:
        return [r.rid for r in self.replicas]

    # -- version wiring: the registry's fan-out surface -------------------

    def set_live(self, engines: Sequence, version: str) -> None:
        """Atomic fleet-wide swap: every replica's router re-points to
        its own engine of `version` under the pick lock, so no batch
        can be dispatched between replica k and k+1 taking the new
        version — a roll never leaves a mixed-version pick window."""
        self._check_fanout(engines)
        with self._cond:
            for rep, eng in zip(self.replicas, engines):
                rep.router.set_live(eng, version)

    def set_shadow(self, engines: Sequence, version: str,
                   fraction: float) -> None:
        self._check_fanout(engines)
        with self._cond:
            for rep, eng in zip(self.replicas, engines):
                rep.router.set_shadow(eng, version, fraction)

    def set_canary(self, engines: Sequence, version: str,
                   fraction: float) -> None:
        self._check_fanout(engines)
        with self._cond:
            for rep, eng in zip(self.replicas, engines):
                rep.router.set_canary(eng, version, fraction)

    def clear_candidates(self) -> None:
        with self._cond:
            for rep in self.replicas:
                rep.router.clear_candidates()

    def _check_fanout(self, engines: Sequence) -> None:
        if len(engines) != len(self.replicas):
            raise ValueError(
                f"fan-out needs one engine per replica: got "
                f"{len(engines)} for {len(self.replicas)} replicas")

    def live_version(self) -> Optional[str]:
        return self.replicas[0].router.live_version()

    def live_infer_dtype(self) -> Optional[str]:
        # identical across replicas (version rolls fan out under the
        # pick lock); replica 0 speaks for all
        return self.replicas[0].router.live_infer_dtype()

    def live_route(self) -> tuple:
        """(live version, infer_dtype) atomically — the prediction
        cache's key basis (ISSUE 10); replica 0 speaks for all, same
        as routes()."""
        return self.replicas[0].router.live_route()

    def routes(self) -> dict:
        # identical across replicas by construction (every mutation
        # fans out under the fleet lock); replica 0 speaks for all
        return self.replicas[0].router.routes()

    def versions_in_route(self) -> set:
        out: set = set()
        for rep in self.replicas:
            out |= rep.router.versions_in_route()
        return out

    def bucket_costs(self) -> dict:
        return self.replicas[0].router.bucket_costs()

    def bucket_costs_p95(self) -> dict:
        return self.replicas[0].router.bucket_costs_p95()

    # -- the pick ----------------------------------------------------------

    def _cost(self, bucket: int) -> float:
        costs = self.bucket_costs()
        return costs.get(bucket, 0.0) if costs else 0.0

    def _pick(self, cost_s: float, exclude: frozenset = frozenset(),
              block: bool = True, overflow: bool = False,
              healthy_only: bool = False) -> Optional[_Replica]:
        """Reserve a dispatch slot on the best replica. Healthy (not
        breaker-cooled) active replicas with free window credit win by
        least outstanding cost; every replica tripped degrades to
        least-loaded among active (limp mode — never a self-inflicted
        outage). block=True (the primary dispatch path) waits for
        credit; block=False (failover/hedge, called on the completion
        thread which is the very thread that frees credit — waiting
        would deadlock) returns None, or over-commits when `overflow`
        (a rescue may transiently exceed the window; a hedge may not).
        The slot (inflight + outstanding cost) is reserved HERE, under
        the lock, so concurrent pickers can never oversubscribe a
        replica past its window."""
        with self._cond:
            while True:
                active = [r for r in self.replicas
                          if r.state == "active" and r.rid not in exclude]
                if not active:
                    if exclude:
                        return None       # no sibling to rescue/hedge on
                    raise NoReplicaAvailable(
                        "every replica is draining — fleet takes no new "
                        "work")
                # the selection policy itself is the shared pure
                # function (the gateway's balanced dispatch runs the
                # SAME one across worker processes); reservation stays
                # here, under this fleet's lock
                rep = select_member(active, self.breaker.in_cooldown,
                                    self.per_replica_inflight,
                                    overflow=(not block and overflow),
                                    healthy_only=healthy_only)
                if rep is not None:
                    self._pick_seq += 1
                    rep.last_pick = self._pick_seq
                    rep.inflight += 1
                    rep.outstanding_s += cost_s
                    return rep
                if healthy_only and all(self.breaker.in_cooldown(r.rid)
                                        for r in active):
                    # hedge picks: a duplicate on a breaker-tripped
                    # sibling is guaranteed wasted work — better no
                    # hedge than a sick one. Rescues and primary
                    # dispatch still get limp mode inside the policy.
                    return None
                if not block:
                    return None
                self._cond.wait(0.05)

    def _release(self, rep: _Replica, cost_s: float) -> None:
        with self._cond:
            rep.inflight -= 1
            rep.outstanding_s = max(rep.outstanding_s - cost_s, 0.0)
            self._cond.notify_all()

    def _mark_dispatched(self, rep: _Replica, rows: int) -> None:
        with self._cond:
            rep.dispatched_batches += 1
            rep.dispatched_rows += rows

    def _record(self, rep: _Replica, ok: bool, n: int = 1,
                latency_s: Optional[float] = None) -> None:
        """One replica-attributed outcome: feeds the health window and
        the per-replica breaker; a trip is logged + counted (the pick
        excludes the replica for the cooldown — no rollback here, a
        sick replica is routed around, not demoted: sick replica !=
        sick version)."""
        self.health.record(rep.rid, ok, n=n, latency_s=latency_s)
        if not ok:
            with self._cond:
                rep.failures += 1
        if self.breaker.record(rep.rid, ok, n=n):
            with self._cond:
                self._replica_trips += 1
            log.warning(
                "fleet: replica %s TRIPPED its breaker — excluded from "
                "dispatch for %.1fs (siblings absorb its share)",
                rep.rid, self.breaker.cooldown_s)
            if self.metrics is not None:
                self.metrics.record_replica_trip(rep.rid)

    # -- the engine surface the batcher drives -----------------------------

    def dispatch(self, x) -> FleetHandle:
        parts = ([self._as_images(p) for p in x]
                 if isinstance(x, (list, tuple))
                 else [self._as_images(x)])
        n = sum(p.shape[0] for p in parts)
        bucket = self.bucket_for(n)
        cost_s = self._cost(bucket)
        rep = self._pick(cost_s)
        try:
            return self._dispatch_on(rep, parts, n, bucket, cost_s)
        except Exception as e:
            self._release(rep, cost_s)
            if getattr(e, "status", None) == 503:
                raise             # systemic: every sibling would refuse
            self._record(rep, ok=False)
            sib = self._pick(cost_s, exclude=frozenset((rep.rid,)),
                             block=False, overflow=True)
            if sib is None:
                raise
            # The rescue span names BOTH replicas (ISSUE 9): after an
            # availability dip, "which replica died and who caught the
            # batch" is the first question a trace must answer.
            sp = trace.begin_span("fleet.failover.dispatch",
                                  from_replica=rep.rid,
                                  to_replica=sib.rid)
            try:
                fh = self._dispatch_on(sib, parts, n, bucket, cost_s)
            except Exception as e2:
                self._release(sib, cost_s)
                self._record(sib, ok=False)
                # same root-cause rule as the fetch rescue: the batch
                # is attributed to its PRIMARY failure, the failed
                # rescue is logged. The span is errored EXPLICITLY:
                # what propagates is the original cause, which the
                # span's own ambient-exception check would not count.
                trace.end_span(sp, error=type(e2).__name__)
                log.warning("fleet: rescue dispatch on %s failed too "
                            "(%s)", sib.rid, e2)
                raise e
            finally:
                trace.end_span(sp)
            with self._cond:
                self._failovers_dispatch += 1
            if self.metrics is not None:
                self.metrics.record_failover("dispatch", rep.rid, sib.rid)
            log.warning("fleet: dispatch failover %s -> %s (%s)",
                        rep.rid, sib.rid, e)
            return fh

    def _dispatch_on(self, rep: _Replica, parts: list, n: int,
                     bucket: int, cost_s: float) -> FleetHandle:
        """One replica-targeted dispatch (slot already reserved by the
        caller's pick; the caller releases it on failure)."""
        failpoint("replica.dispatch", replica=rep.rid, rows=n,
                  bucket=bucket)
        rh = rep.router.dispatch(parts)
        self._mark_dispatched(rep, n)
        return FleetHandle(inner=rh, replica=rep.rid, version=rh.version,
                           n=rh.n, bucket=rh.bucket, x=parts,
                           cost_s=cost_s, t_dispatch=time.monotonic(),
                           infer_dtype=getattr(rh, "infer_dtype", None))

    def _fetch_on(self, rep: _Replica, fh_or_rh, version, n: int
                  ) -> np.ndarray:
        failpoint("replica.fetch", replica=rep.rid, version=version,
                  rows=n)
        return rep.router.fetch(fh_or_rh)

    def _drain_abandoned(self, rep: _Replica, inner) -> None:
        """A replica-targeted fetch died and its handle will never be
        fetched again by the pipeline (failover moved the batch to a
        sibling, or both hedge arms failed). If the death happened
        BEFORE the engine's own fetch ran — the replica.fetch
        failpoint, the chaos kill — the handle still pins a checked-out
        staging buffer. Fetch-and-discard it on a detached daemon
        thread, exactly the hedge-loser pattern: engine.fetch recycles
        in its finally whether it succeeds, raises, or was already
        fetched, and a wedged victim must not stall the rescue.
        Without this, every killed fetch leaked one pooled buffer —
        the PR 5 class on the fleet path, pinned by the sanitizer's
        engine.staging balance.

        Handles whose ENGINE fetch already ran (a real fetch error:
        the engine recycled staging in its finally, and Router.fetch's
        except branch already drained the shadow duplicate) are
        SKIPPED, not re-fetched: a second Router.fetch would
        double-enqueue the same shadow comparison and drift the
        router's _shadow_pending claim count negative. An engine-
        fetched InferenceHandle has staging None — the one-shot
        marker; doubles without the attribute always drain."""
        h = getattr(inner, "handle", inner)
        if getattr(h, "staging", "never-fetched") is None:
            return

        def drain():
            try:
                rep.router.fetch(inner)
            except Exception:
                pass

        make_thread(target=drain, name="serve-drain-abandoned",
                    daemon=True).start()

    def fetch(self, fh: FleetHandle) -> np.ndarray:
        rep = self._by_id[fh.replica]
        if self.hedge:
            threshold = self._hedge_threshold(fh.bucket)
            if (threshold is not None
                    and time.monotonic() - fh.t_dispatch > threshold):
                sib = self._pick(fh.cost_s,
                                 exclude=frozenset((rep.rid,)),
                                 block=False, overflow=False,
                                 healthy_only=True)
                if sib is not None:
                    return self._fetch_hedged(fh, rep, sib)
        try:
            out = self._fetch_on(rep, fh.inner, fh.version, fh.n)
        except Exception as e:
            self._release(rep, fh.cost_s)
            if getattr(e, "status", None) == 503:
                raise             # systemic: not this replica's fault
            self._record(rep, ok=False)
            return self._failover_fetch(fh, rep, e)
        self._release(rep, fh.cost_s)
        self._record(rep, ok=True,
                     latency_s=time.monotonic() - fh.t_dispatch)
        return out

    def _failover_fetch(self, fh: FleetHandle, failed: _Replica,
                        cause: Exception) -> np.ndarray:
        """The batch's replica died at fetch: redispatch the retained
        payload once on a healthy sibling, inline (the completion
        thread is already dedicated to this batch — FIFO order is
        preserved, the rescue just extends this batch's service time).
        The sibling pick may over-commit its window: rescuing held work
        beats strict admission. A second failure propagates — the
        batcher's bisection/breaker path takes over, exactly as if the
        fleet were a single engine that failed."""
        self._drain_abandoned(failed, fh.inner)
        sib = self._pick(fh.cost_s, exclude=frozenset((failed.rid,)),
                         block=False, overflow=True)
        if sib is None:
            raise cause
        # A failed rescue propagates the ORIGINAL cause: the batch's
        # root failure is the primary's fault, and the client-visible
        # (and bench-classified) outcome must name it — a rescue dying
        # of something else (say an injected fault matched on the
        # rescuing replica while the primary died of a version fault)
        # is a secondary event that belongs in the log, not in the
        # batch's attribution. The rescue span names both replicas
        # (ISSUE 9) and times the whole redispatch+fetch, so a
        # rescued request's tail is blamed on the rescue, not on the
        # enclosing fetch stage.
        sp = trace.begin_span("fleet.failover.fetch",
                              from_replica=failed.rid,
                              to_replica=sib.rid)
        try:
            try:
                rescued = self._dispatch_on(sib, fh.x, fh.n, fh.bucket,
                                            fh.cost_s)
            except Exception as e2:
                self._release(sib, fh.cost_s)
                self._record(sib, ok=False)
                trace.end_span(sp, error=type(e2).__name__)
                log.warning("fleet: rescue dispatch on %s failed too "
                            "(%s)", sib.rid, e2)
                raise cause
            log.warning("fleet: fetch failover %s -> %s (%s)",
                        failed.rid, sib.rid, cause)
            try:
                out = self._fetch_on(sib, rescued.inner, rescued.version,
                                     fh.n)
            except Exception as e2:
                self._release(sib, fh.cost_s)
                self._record(sib, ok=False)
                self._drain_abandoned(sib, rescued.inner)
                trace.end_span(sp, error=type(e2).__name__)
                log.warning("fleet: rescue fetch on %s failed too (%s)",
                            sib.rid, e2)
                raise cause
        finally:
            trace.end_span(sp)
        self._release(sib, fh.cost_s)
        # The sibling's health is scored on ITS OWN service time (the
        # rescue dispatch onward): charging the dead primary's delay to
        # the replica that saved the batch would point the per-replica
        # latency signal at the wrong replica.
        self._record(sib, ok=True,
                     latency_s=time.monotonic() - rescued.t_dispatch)
        # A failover is counted only once the rescue actually LANDED
        # (dispatch + fetch): the counter's contract is "batches
        # redundancy saved", and a rescue that fails the same way the
        # primary did (e.g. a version-pinned fault present on every
        # replica) saved nothing.
        with self._cond:
            self._failovers_fetch += 1
        if self.metrics is not None:
            self.metrics.record_failover("fetch", failed.rid, sib.rid)
        # Attribution follows the computation: the sibling's version
        # may differ from the original dispatch's (a roll landed in
        # between) — the re-tag keeps by_version/by_replica honest.
        fh.replica, fh.version = sib.rid, rescued.version
        fh.infer_dtype = rescued.infer_dtype
        return out

    def _hedge_threshold(self, bucket: int) -> Optional[float]:
        p95 = self.bucket_costs_p95()
        if not p95 or bucket not in p95:
            return None           # no tail estimate yet: never hedge
        return self.hedge_factor * p95[bucket]

    def _fetch_hedged(self, fh: FleetHandle, rep: _Replica,
                      sib: _Replica) -> np.ndarray:
        """Race the overdue primary fetch against a duplicate on `sib`
        (slot already reserved by the caller's pick): first success
        wins, the loser finishes on its own daemon thread — its
        engine recycles staging in fetch()'s finally, its accounting
        lands in its runner, nothing leaks. Hedges are rare by
        construction (past the p95 threshold AND a free healthy
        sibling), so the two short-lived threads per hedge are noise."""
        cv = make_condition("fleet.hedge")
        results: dict = {}            # tag -> (ok, value) in arrival order
        winner: dict = {}             # the hedge span's winner tag

        def finish(tag, ok, value):
            with cv:
                results[tag] = (ok, value)
                cv.notify_all()

        # The race's parent span plus one child per arm (ISSUE 9): the
        # arms run on their own threads, so they take an explicit ctx
        # ref instead of inheriting from a thread-local stack.
        hsp = trace.begin_span("fleet.hedge", primary=rep.rid,
                               duplicate=sib.rid, bucket=fh.bucket)
        try:
            ctx = trace.current()

            def run_primary():
                psp = trace.begin_span("fleet.hedge.primary", ctx=ctx,
                                       replica=rep.rid)
                try:
                    try:
                        out = self._fetch_on(rep, fh.inner, fh.version,
                                             fh.n)
                    except Exception as e:
                        self._release(rep, fh.cost_s)
                        self._record(rep, ok=False)
                        self._drain_abandoned(rep, fh.inner)
                        finish("primary", False, e)
                        return
                    self._release(rep, fh.cost_s)
                    self._record(rep, ok=True,
                                 latency_s=(time.monotonic()
                                            - fh.t_dispatch))
                    finish("primary", True, out)
                finally:
                    trace.end_span(psp)

            def run_hedge():
                dsp = trace.begin_span("fleet.hedge.duplicate", ctx=ctx,
                                       replica=sib.rid)
                try:
                    try:
                        dup = self._dispatch_on(sib, fh.x, fh.n,
                                                fh.bucket, fh.cost_s)
                    except Exception as e:
                        self._release(sib, fh.cost_s)
                        self._record(sib, ok=False)
                        finish("hedge", False, e)
                        return
                    try:
                        out = self._fetch_on(sib, dup.inner, dup.version,
                                             fh.n)
                    except Exception as e:
                        self._release(sib, fh.cost_s)
                        self._record(sib, ok=False)
                        self._drain_abandoned(sib, dup.inner)
                        finish("hedge", False, e)
                        return
                    self._release(sib, fh.cost_s)
                    # scored on the hedge's own dispatch-to-result
                    # time, not the overdue primary's elapsed window
                    # (same attribution rule as the failover rescue)
                    self._record(sib, ok=True,
                                 latency_s=(time.monotonic()
                                            - dup.t_dispatch))
                    finish("hedge", True, (out, dup.version, sib.rid,
                                           dup.infer_dtype))
                finally:
                    trace.end_span(dsp)

            with self._cond:
                self._hedges += 1
            for target in (run_primary, run_hedge):
                make_thread(target=target, name="serve-hedge",
                            daemon=True).start()
            with cv:
                while True:
                    for tag, (ok, value) in results.items():
                        if ok:
                            hedge_won = tag == "hedge"
                            winner["who"] = tag
                            if hedge_won:
                                with self._cond:
                                    self._hedge_wins += 1
                                out, version, rid, dtype = value
                                fh.replica, fh.version = rid, version
                                fh.infer_dtype = dtype
                            else:
                                out = value
                            if self.metrics is not None:
                                self.metrics.record_hedge(win=hedge_won)
                            return out
                    if len(results) == 2:   # both failed
                        if self.metrics is not None:
                            self.metrics.record_hedge(win=False)
                        raise results["primary"][1]
                    cv.wait()
        finally:
            trace.end_span(hsp, winner=winner.get("who"))

    def infer(self, x) -> np.ndarray:
        return self.fetch(self.dispatch(x))

    # -- admin: drain / rejoin --------------------------------------------

    def drain(self, rid: str) -> dict:
        """Stop picking `rid`: no new dispatches, no rescue or hedge
        targets land on it either (both go through the pick). Batches
        it already holds finish normally — fetch doesn't pick — so the
        window empties on its own. Refuses to drain the last active
        replica: that is 'shut the service down', which has its own
        signal."""
        with self._cond:
            rep = self._get(rid)
            if rep.state != "draining":
                others = [r for r in self.replicas
                          if r.state == "active" and r.rid != rid]
                if not others:
                    raise RuntimeError(
                        f"refusing to drain {rid}: it is the last active "
                        "replica (SIGTERM the server to stop serving)")
                rep.state = "draining"
                self._cond.notify_all()
            snap = self._replica_snapshot(rep)
        log.info("fleet: replica %s draining (%d in flight)", rid,
                 snap["inflight"])
        return snap

    def rejoin(self, rid: str) -> dict:
        """Return a drained replica to the pick set with a FRESH health
        slate (breaker window + cooldown + tracker cleared): the
        operator asserting 'repaired' must not be vetoed by failures
        recorded before the repair."""
        with self._cond:
            rep = self._get(rid)
            rep.state = "active"
            self._cond.notify_all()
        self.breaker.reset(rid)
        self.health.reset(rid)
        log.info("fleet: replica %s rejoined", rid)
        with self._cond:
            return self._replica_snapshot(rep)

    def _get(self, rid: str) -> _Replica:
        rep = self._by_id.get(rid)
        if rep is None:
            raise KeyError(f"unknown replica {rid!r}; fleet has "
                           f"{self.replica_ids()}")
        return rep

    # -- introspection -----------------------------------------------------

    def _replica_snapshot(self, rep: _Replica) -> dict:
        # caller holds self._cond
        return {
            "id": rep.rid,
            "state": rep.state,
            "healthy": not self.breaker.in_cooldown(rep.rid),
            "health_score": round(self.health.score(rep.rid), 4),
            "inflight": rep.inflight,
            "outstanding_cost_ms": round(rep.outstanding_s * 1e3, 3),
            "dispatched_batches": rep.dispatched_batches,
            "dispatched_rows": rep.dispatched_rows,
            "failures": rep.failures,
        }

    def snapshot(self) -> dict:
        """The /healthz + /metrics fleet block: per-replica state and
        the fleet-level failover/hedge counters."""
        with self._cond:
            replicas = [self._replica_snapshot(r) for r in self.replicas]
            out = {
                "n_replicas": len(self.replicas),
                "per_replica_inflight": self.per_replica_inflight,
                "hedge": self.hedge,
                "replicas": replicas,
                "failovers": {"dispatch": self._failovers_dispatch,
                              "fetch": self._failovers_fetch},
                "hedges": {"fired": self._hedges,
                           "wins": self._hedge_wins},
                "replica_trips": self._replica_trips,
            }
        out["breaker"] = self.breaker.snapshot()
        out["health"] = self.health.snapshot()
        return out
