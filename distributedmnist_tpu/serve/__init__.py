"""Batched inference serving (ISSUES 1-3): the forward-only half of the
north star's "serves heavy traffic from millions of users".

- engine.py   bucketed, jitted, donated forward step over the 'data' mesh,
              split into dispatch()/fetch() around the async device queue;
              warmup measures a per-bucket cost table
- batcher.py  dynamic micro-batcher pipelined through a bounded in-flight
              window, with bounded-queue backpressure and the
              single-request bypass fast lane (ISSUE 14: empty queue +
              free slot -> dispatch on the caller's thread)
- scheduler.py cost-model batch former (split-vs-pad planning) and the
              Clipper-style AIMD adaptive-coalescing controller
- metrics.py  latency percentiles / occupancy / qps / pipeline depth,
              staging-vs-fetch split, per-version populations and
              shadow-comparison aggregates, JSON-line records
- registry.py checkpoint-backed versioned model store: params-only
              restore, pre-warmed engines, atomic promotion, eviction,
              rollback events
- router.py   version-aware dispatch between batcher and engines:
              hot-swap, shadow duplication, canary splitting
- quantize.py the low-precision inference fast path (ISSUE 7): per-
              output-channel int8 weight quantization + the bf16/int8
              inference-specialized forwards, served only behind the
              registry's accuracy-parity gate
- faults.py   config-driven fault injection: named failpoints woven
              through every serving layer, fully inert when disabled
- resilience.py deadline shedding, poison-batch bisection policy, the
              per-version circuit breaker with auto-rollback, and the
              sliding-window HealthTracker the fleet scores replicas by
- fleet.py    fault-tolerant replica set (ISSUE 6): health-tracked
              cost-aware dispatch over N per-replica routers, failover
              redispatch, hedged tails, drain/rejoin
- trace.py    end-to-end request tracing (ISSUE 9): request-scoped
              span trees woven through every layer above, head
              sampling with error/over-SLO exemplars, Chrome
              trace-event export, stage attribution, and the
              per-stage histograms behind /metrics' Prometheus surface
- cache.py    prediction cache + single-flight dedup front layer
              (ISSUE 10): bounded LRU keyed by (live version,
              infer_dtype, content hash), concurrent identical misses
              collapsed onto one in-flight computation, registry-
              invalidated atomically on every live-route change
- tenancy.py  multi-tenant, multi-model serving (ISSUE 18): the
              ModelCatalog hosting independent serving stacks per
              model, token-bucket admission per tenant SLO class, and
              the Clockwork-style global scheduler — weighted deficit
              round robin across tenants, earliest-feasible-deadline
              across each tenant's model queues, dispatch priced by
              the measured per-bucket cost tables, infeasible heads
              shed NOW, cold models warmed as priced scheduled events
- gateway.py  horizontal scale-out front door (ISSUE 19): routes HTTP
              across N spawned serve.py worker processes on a
              consistent-hash ring keyed like the prediction cache
              (hot keys shard across worker caches, not duplicate),
              least-loaded fallback via the fleet's shared pick
              policy, one failover redispatch on worker death, and
              two-phase fleet-wide promote under a cluster epoch that
              rejects mixed-epoch replies

Imports stay lazy (PEP 562, like utils/): pulling `serve` in a supervisor
parent must not import jax.
"""

_EXPORTS = {
    "InferenceEngine": ("distributedmnist_tpu.serve.engine",
                        "InferenceEngine"),
    "InferenceHandle": ("distributedmnist_tpu.serve.engine",
                        "InferenceHandle"),
    "build_engine": ("distributedmnist_tpu.serve.engine", "build_engine"),
    "build_model_and_mesh": ("distributedmnist_tpu.serve.engine",
                             "build_model_and_mesh"),
    "make_buckets": ("distributedmnist_tpu.serve.engine", "make_buckets"),
    "DynamicBatcher": ("distributedmnist_tpu.serve.batcher",
                       "DynamicBatcher"),
    "Rejected": ("distributedmnist_tpu.serve.batcher", "Rejected"),
    "resolve_max_inflight": ("distributedmnist_tpu.serve.batcher",
                             "resolve_max_inflight"),
    "ServeMetrics": ("distributedmnist_tpu.serve.metrics", "ServeMetrics"),
    "AdaptiveController": ("distributedmnist_tpu.serve.scheduler",
                           "AdaptiveController"),
    "plan_segments": ("distributedmnist_tpu.serve.scheduler",
                      "plan_segments"),
    "EngineFactory": ("distributedmnist_tpu.serve.registry",
                      "EngineFactory"),
    "ModelRegistry": ("distributedmnist_tpu.serve.registry",
                      "ModelRegistry"),
    "ModelVersion": ("distributedmnist_tpu.serve.registry",
                     "ModelVersion"),
    "build_serving": ("distributedmnist_tpu.serve.registry",
                      "build_serving"),
    "Router": ("distributedmnist_tpu.serve.router", "Router"),
    "RoutedHandle": ("distributedmnist_tpu.serve.router", "RoutedHandle"),
    "NoLiveModel": ("distributedmnist_tpu.serve.router", "NoLiveModel"),
    "FaultInjector": ("distributedmnist_tpu.serve.faults",
                      "FaultInjector"),
    "FaultRule": ("distributedmnist_tpu.serve.faults", "FaultRule"),
    "InjectedFault": ("distributedmnist_tpu.serve.faults",
                      "InjectedFault"),
    "CircuitBreaker": ("distributedmnist_tpu.serve.resilience",
                       "CircuitBreaker"),
    "DeadlineExceeded": ("distributedmnist_tpu.serve.resilience",
                         "DeadlineExceeded"),
    "ResiliencePolicy": ("distributedmnist_tpu.serve.resilience",
                         "ResiliencePolicy"),
    "build_resilience": ("distributedmnist_tpu.serve.resilience",
                         "build_resilience"),
    "HealthTracker": ("distributedmnist_tpu.serve.resilience",
                      "HealthTracker"),
    "quantize_channelwise": ("distributedmnist_tpu.serve.quantize",
                             "quantize_channelwise"),
    "prepare_inference": ("distributedmnist_tpu.serve.quantize",
                          "prepare_inference"),
    "INFER_DTYPES": ("distributedmnist_tpu.serve.quantize",
                     "INFER_DTYPES"),
    "VariantInfo": ("distributedmnist_tpu.serve.registry",
                    "VariantInfo"),
    "PARITY_GATES": ("distributedmnist_tpu.serve.registry",
                     "PARITY_GATES"),
    "ReplicaSet": ("distributedmnist_tpu.serve.fleet", "ReplicaSet"),
    "FleetHandle": ("distributedmnist_tpu.serve.fleet", "FleetHandle"),
    "NoReplicaAvailable": ("distributedmnist_tpu.serve.fleet",
                           "NoReplicaAvailable"),
    "Tracer": ("distributedmnist_tpu.serve.trace", "Tracer"),
    "attribute_stages": ("distributedmnist_tpu.serve.trace",
                         "attribute_stages"),
    "prometheus_exposition": ("distributedmnist_tpu.serve.metrics",
                              "prometheus_exposition"),
    "PredictionCache": ("distributedmnist_tpu.serve.cache",
                        "PredictionCache"),
    "CacheFront": ("distributedmnist_tpu.serve.cache", "CacheFront"),
    "content_key": ("distributedmnist_tpu.serve.cache", "content_key"),
    "build_cache_front": ("distributedmnist_tpu.serve.cache",
                          "build_cache_front"),
    "ModelCatalog": ("distributedmnist_tpu.serve.tenancy",
                     "ModelCatalog"),
    "GlobalScheduler": ("distributedmnist_tpu.serve.tenancy",
                        "GlobalScheduler"),
    "QuotaExceeded": ("distributedmnist_tpu.serve.tenancy",
                      "QuotaExceeded"),
    "SLOClass": ("distributedmnist_tpu.serve.tenancy", "SLOClass"),
    "parse_tenants": ("distributedmnist_tpu.serve.tenancy",
                      "parse_tenants"),
    "build_catalog": ("distributedmnist_tpu.serve.tenancy",
                      "build_catalog"),
    "build_tenancy": ("distributedmnist_tpu.serve.tenancy",
                      "build_tenancy"),
    "Gateway": ("distributedmnist_tpu.serve.gateway", "Gateway"),
    "HashRing": ("distributedmnist_tpu.serve.gateway", "HashRing"),
    "ring_key": ("distributedmnist_tpu.serve.gateway", "ring_key"),
    "gateway_prometheus_exposition": (
        "distributedmnist_tpu.serve.metrics",
        "gateway_prometheus_exposition"),
    "select_member": ("distributedmnist_tpu.serve.fleet",
                      "select_member"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(module_name), attr)
