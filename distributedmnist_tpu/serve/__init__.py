"""Batched inference serving (ISSUE 1): the forward-only half of the
north star's "serves heavy traffic from millions of users".

- engine.py   bucketed, jitted, donated forward step over the 'data' mesh,
              split into dispatch()/fetch() around the async device queue
- batcher.py  dynamic micro-batcher pipelined through a bounded in-flight
              window, with bounded-queue backpressure
- metrics.py  latency percentiles / occupancy / qps / pipeline depth and
              staging-vs-fetch split, JSON-line records

Imports stay lazy (PEP 562, like utils/): pulling `serve` in a supervisor
parent must not import jax.
"""

_EXPORTS = {
    "InferenceEngine": ("distributedmnist_tpu.serve.engine",
                        "InferenceEngine"),
    "InferenceHandle": ("distributedmnist_tpu.serve.engine",
                        "InferenceHandle"),
    "build_engine": ("distributedmnist_tpu.serve.engine", "build_engine"),
    "make_buckets": ("distributedmnist_tpu.serve.engine", "make_buckets"),
    "DynamicBatcher": ("distributedmnist_tpu.serve.batcher",
                       "DynamicBatcher"),
    "Rejected": ("distributedmnist_tpu.serve.batcher", "Rejected"),
    "resolve_max_inflight": ("distributedmnist_tpu.serve.batcher",
                             "resolve_max_inflight"),
    "ServeMetrics": ("distributedmnist_tpu.serve.metrics", "ServeMetrics"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(module_name), attr)
